"""Closed-loop tuner: knob layer contract, controller state machine,
shadow A/B guard, concurrency, and the jax-free `mesh-tpu tune` CLI.

Every clock read in the loop goes through the injected ``clock``, so
the whole widen / fast-burn-shrink / auto-revert policy runs under a
fake clock with no sleeps (ISSUE-13 acceptance).  Each state-machine
test asserts the audited side effects too: the ``knob_change``
flight-recorder event and the ``mesh_tpu_tuner_*`` series deltas.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from mesh_tpu import obs
from mesh_tpu.obs import controller as controller_mod
from mesh_tpu.obs.controller import LATENCY_METRIC, TunerController
from mesh_tpu.obs.recorder import FlightRecorder, get_recorder
from mesh_tpu.obs.series import WindowedSeries
from mesh_tpu.utils import lockwitness, tuning
from mesh_tpu.utils.lockwitness import _WitnessedLock

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every env var whose presence would pin a tunable or reconfigure the
#: loop out from under the fake-clock tests
_TUNER_ENV = (
    "MESH_TPU_TUNER", "MESH_TPU_TUNER_INTERVAL", "MESH_TPU_TUNER_AB_TOL",
    "MESH_TPU_KNOB_TAIL", "MESH_TPU_COALESCE_WINDOW_MS",
    "MESH_TPU_ACCEL_MIN_FACES", "MESH_TPU_MXU_CROSSOVER_FACES",
    "MESH_TPU_BVH_STREAM_BUFFERS",
    "MESH_TPU_SERVE_LADDER", "MESH_TPU_RECORDER",
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    for var in _TUNER_ENV:
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("MESH_TPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    obs.reset()
    yield
    obs.reset()


class _FakeMonitor(object):
    """Scripted SLOMonitor stand-in: one fast-burn row at a settable
    pressure (the only fields pressure() reads)."""

    def __init__(self, pressure=0.0):
        self.pressure = pressure

    def burn_rates(self, now=None):
        return [{"objective": "latency_p99", "tenant": None,
                 "rule": "fast_burn", "pressure": self.pressure}]


class _Loop(object):
    """Fake-clock harness: global registry + recorder (where actuate's
    audit trail lands), a private windowed series, a scripted monitor."""

    def __init__(self, **ctrl_kw):
        self.t = [0.0]
        clock = lambda: self.t[0]
        self.hist = obs.REGISTRY.histogram(
            LATENCY_METRIC, "serve latency (test)")
        self.series = WindowedSeries(
            registry=obs.REGISTRY, resolution_s=1.0, capacity=512,
            clock=clock)
        self.monitor = _FakeMonitor()
        ctrl_kw.setdefault("ab_tol", 0.2)
        ctrl_kw.setdefault("holdout_s", 30.0)
        self.ctrl = TunerController(
            series=self.series, monitor=self.monitor, clock=clock,
            **ctrl_kw)

    def feed(self, now, latency_s=0.01, n=8):
        for _ in range(n):
            self.hist.observe(latency_s, tenant="t", backend="bvh")
        self.series.tick(now=now)

    def step(self, now, latency_s=0.01, feed=True):
        self.t[0] = now
        if feed:
            self.feed(now, latency_s)
        return self.ctrl.step(now=now)


def _knob_changes(knob=None):
    events = [e for e in get_recorder().events()
              if e.get("kind") == "knob_change"]
    if knob is not None:
        events = [e for e in events if e["knob"] == knob]
    return events


def _counter(name, **labels):
    metric = obs.REGISTRY.get(name)
    return 0 if metric is None else metric.value(**labels)


# -- the tunable-knob layer (utils/tuning.py) --------------------------

def test_env_pin_wins_and_refuses_actuation(monkeypatch):
    monkeypatch.setenv("MESH_TPU_COALESCE_WINDOW_MS", "7.5")
    assert tuning.pinned("coalesce_window_ms")
    assert tuning.get("coalesce_window_ms") == 7.5
    assert tuning.tuned_value("coalesce_window_ms") is None
    # the operator's pin beats the controller: actuation is refused
    assert tuning.actuate("coalesce_window_ms", 3.0, reason="t") is None
    assert tuning.generation() == 0
    assert tuning.get("coalesce_window_ms") == 7.5


def test_pin_means_default_for_explicit_ladder(monkeypatch):
    # an explicit MESH_TPU_SERVE_LADDER pins the pre-trip bit at its
    # default (0) — the var configures the ladder, not the tunable
    monkeypatch.setenv("MESH_TPU_SERVE_LADDER", "grid,brute")
    assert tuning.pinned("serve_pre_trip")
    assert tuning.get("serve_pre_trip") == 0
    assert tuning.actuate("serve_pre_trip", 1, reason="t") is None


def test_kill_switch_freezes_static_defaults(monkeypatch):
    assert tuning.actuate("coalesce_window_ms", 5.0, reason="t")
    assert tuning.get("coalesce_window_ms") == 5.0
    monkeypatch.setenv("MESH_TPU_TUNER", "0")
    # every tunable reads its static default; nothing moves
    assert tuning.get("coalesce_window_ms") == 0.0
    assert tuning.tuned_value("coalesce_window_ms") is None
    assert tuning.actuate("coalesce_window_ms", 9.0, reason="t") is None
    for row in tuning.status()["knobs"]:
        assert row["value"] == row["default"] and not row["tuned"]
    # and the controller short-circuits before reading anything
    loop = _Loop()
    assert loop.ctrl.step(now=1.0) == {"mode": "disabled", "actions": []}
    assert loop.ctrl.start() is loop.ctrl and loop.ctrl._thread is None


def test_actuate_clamps_audits_and_moves_series():
    event = tuning.actuate(
        "coalesce_window_ms", 99.0, reason="unit", evidence={"k": 1},
        now=3.0)
    assert event["after"] == 20.0          # clamped to the declared hi
    assert event["before"] == 0.0
    assert event["action"] == "set" and event["generation"] == 1
    assert event["t"] == 3.0 and event["evidence"] == {"k": 1}
    # no-op writes don't churn the generation or the audit trail
    assert tuning.actuate("coalesce_window_ms", 25.0, reason="u") is None
    assert tuning.generation() == 1
    # the audited side effects: recorder event + tuner series (the
    # recorder stamps its own wall "t" and a "kind" on top)
    (recorded,) = _knob_changes("coalesce_window_ms")
    assert {k: v for k, v in recorded.items()
            if k not in ("kind", "t")} == \
        {k: v for k, v in event.items() if k != "t"}
    assert _counter("mesh_tpu_tuner_changes_total",
                    knob="coalesce_window_ms", action="set") == 1
    assert _counter("mesh_tpu_tuner_generation") == 1
    assert _counter("mesh_tpu_tuner_knob_value",
                    knob="coalesce_window_ms") == 20.0
    assert tuning.history_tail(8) == [event]


def test_history_tail_is_bounded_and_oldest_first():
    for step in range(80):
        tuning.actuate("coalesce_window_ms", float(step % 20) + 0.5,
                       reason="r%d" % step)
    tail = tuning.history_tail(4)
    assert len(tail) == 4
    assert [e["generation"] for e in tail] == sorted(
        e["generation"] for e in tail)
    # the deque itself is capped at 64 regardless of the ask
    assert len(tuning.history_tail(1000)) == 64


# -- controller state machine (fake clock, no sleeps) ------------------

def test_throughput_mode_widens_under_ab_guard():
    loop = _Loop()
    res = loop.step(now=15.0)
    assert res["mode"] == "throughput" and res["pressure"] == 0.0
    assert tuning.get("coalesce_window_ms") == 1.0
    (widen,) = res["actions"]
    assert widen["reason"].startswith("throughput_mode: widen")
    assert widen["evidence"]["before_p99_s"] is not None
    assert _counter("mesh_tpu_tuner_evaluations_total",
                    mode="throughput") == 1
    # hold-out pending: the next step must NOT stack a second widen
    res = loop.step(now=30.0)
    assert res["actions"] == []
    assert tuning.get("coalesce_window_ms") == 1.0
    # hold-out expires with steady latency: confirmed, widen resumes
    res = loop.step(now=45.0)
    assert _counter("mesh_tpu_tuner_ab_total",
                    knob="coalesce_window_ms", verdict="confirmed") == 1
    assert tuning.get("coalesce_window_ms") == 2.0
    assert _counter("mesh_tpu_tuner_changes_total",
                    knob="coalesce_window_ms", action="set") == 2
    assert _counter("mesh_tpu_tuner_changes_total",
                    knob="coalesce_window_ms", action="revert") == 0


def test_no_widen_without_traffic_evidence():
    # an idle service has no p99 to protect with the A/B guard — the
    # controller must not churn knobs it cannot judge
    loop = _Loop()
    res = loop.step(now=15.0, feed=False)
    assert res["mode"] == "throughput" and res["actions"] == []
    assert tuning.get("coalesce_window_ms") == 0.0
    assert tuning.generation() == 0


def test_fast_burn_shrinks_and_pre_trips_then_releases():
    assert tuning.actuate("coalesce_window_ms", 5.0, reason="seed")
    loop = _Loop()
    loop.monitor.pressure = 1.2
    res = loop.step(now=15.0)
    assert res["mode"] == "latency"
    assert tuning.get("coalesce_window_ms") == 4.0
    assert tuning.get("serve_pre_trip") == 1
    reasons = [a["reason"] for a in res["actions"]]
    assert any(r.startswith("latency_mode: fast-burn") for r in reasons)
    assert any("pre-trip" in r for r in reasons)
    assert _counter("mesh_tpu_tuner_evaluations_total",
                    mode="latency") == 1
    # sustained burn keeps clawing the window back; pre-trip is level
    res = loop.step(now=30.0)
    assert tuning.get("coalesce_window_ms") == 3.0
    assert [a["knob"] for a in res["actions"]] == ["coalesce_window_ms"]
    # pressure clears: the pre-trip releases through the audited path
    loop.monitor.pressure = 0.0
    res = loop.step(now=45.0)
    assert tuning.get("serve_pre_trip") == 0
    assert any(a["knob"] == "serve_pre_trip" and a["after"] == 0
               for a in res["actions"])
    assert _counter("mesh_tpu_tuner_changes_total",
                    knob="serve_pre_trip", action="set") == 2


def test_regressing_ab_window_auto_reverts():
    loop = _Loop()
    res = loop.step(now=15.0)                      # widen 0 -> 1, guard
    assert tuning.get("coalesce_window_ms") == 1.0
    loop.step(now=30.0, latency_s=0.5)             # hold-out regresses
    res = loop.step(now=45.0, latency_s=0.5)       # guard due: judge
    assert _counter("mesh_tpu_tuner_ab_total",
                    knob="coalesce_window_ms", verdict="reverted") == 1
    revert = next(a for a in res["actions"] if a["action"] == "revert")
    assert revert["after"] == 0.0
    assert "regressed past tolerance" in revert["reason"]
    assert revert["evidence"]["after_p99_s"] > \
        revert["evidence"]["before_p99_s"] * 1.2
    assert _counter("mesh_tpu_tuner_changes_total",
                    knob="coalesce_window_ms", action="revert") == 1
    # the verdict is also flight-recorded with its evidence
    (ab_event,) = [e for e in get_recorder().events()
                   if e.get("kind") == "knob_ab"]
    assert ab_event["verdict"] == "reverted"
    assert ab_event["after_p99_s"] is not None


def test_missing_holdout_evidence_never_reads_as_improvement():
    loop = _Loop()
    loop.step(now=15.0)                            # widen 0 -> 1, guard
    # the hold-out window carries NO traffic at all
    res = loop.step(now=45.0, feed=False)
    assert _counter("mesh_tpu_tuner_ab_total",
                    knob="coalesce_window_ms", verdict="reverted") == 1
    revert = next(a for a in res["actions"] if a["action"] == "revert")
    assert "evidence missing" in revert["reason"]
    assert revert["evidence"]["after_p99_s"] is None


def test_holdout_revert_survives_raising_recorder():
    """step() pops the guard before judging, so _settle_guard is the
    only chance to undo an unconfirmed widen: a telemetry sink that
    dies mid-verdict must not eat the revert (it sits in a finally)."""
    class _BoomRecorder(object):
        def record(self, kind, **fields):
            if kind == "knob_ab":
                raise RuntimeError("telemetry sink down")
            return get_recorder().record(kind, **fields)

    loop = _Loop(recorder=_BoomRecorder())
    loop.step(now=15.0)                            # widen 0 -> 1, guard
    loop.step(now=30.0, latency_s=0.5)             # hold-out regresses
    with pytest.raises(RuntimeError):
        loop.step(now=45.0, latency_s=0.5)         # verdict emit dies
    # ... but the unconfirmed widen was still reverted on the way out
    assert tuning.get("coalesce_window_ms") == 0.0
    assert _counter("mesh_tpu_tuner_changes_total",
                    knob="coalesce_window_ms", action="revert") == 1


def test_latency_shrink_cancels_pending_widen_guard():
    loop = _Loop()
    loop.step(now=15.0)                            # widen 0 -> 1, guard
    loop.monitor.pressure = 1.2
    loop.step(now=30.0)                            # shrink 1 -> 0
    assert tuning.get("coalesce_window_ms") == 0.0
    loop.monitor.pressure = 0.0
    loop.step(now=60.0)                            # past the deadline
    # the superseded hold-out was cancelled, never judged
    for verdict in ("confirmed", "reverted"):
        assert _counter("mesh_tpu_tuner_ab_total",
                        knob="coalesce_window_ms", verdict=verdict) == 0


def test_background_retune_publishes_calibrations():
    calls = []

    def hook():
        calls.append(1)
        return 100, {"source": "calib.json", "key": "accel_min_faces"}

    loop = _Loop(retune_fns={"accel_min_faces": hook}, retune_every=1)
    loop.step(now=15.0, feed=False)
    assert calls
    # published through actuate: clamped to the declared floor, audited
    assert tuning.tuned_value("accel_min_faces") == 4096
    (event,) = _knob_changes("accel_min_faces")
    assert event["reason"] == "retune: autotune calibration"
    assert event["evidence"]["key"] == "accel_min_faces"
    # a hook with nothing measured (None) or a raising hook is skipped
    boom = lambda: (_ for _ in ()).throw(RuntimeError("x"))
    loop = _Loop(retune_fns={"stream_n_buffers": lambda: None,
                             "accel_min_faces": boom}, retune_every=1)
    res = loop.step(now=30.0, feed=False)
    assert res["actions"] == []


def test_mxu_crossover_retune_bounded_and_pinned(monkeypatch):
    """The mxu_crossover tunable rides the standard retune path under
    the fake clock: actuate clamps to the declared bounds, the audit
    event lands, and the operator's env pin silently wins."""
    def hook():
        # below the declared 1024-face floor: actuate must clamp
        return 512, {"source": "mxu_crossover_calib.json",
                     "key": "mxu_crossover_faces"}

    loop = _Loop(retune_fns={"mxu_crossover": hook}, retune_every=1)
    loop.step(now=15.0, feed=False)
    assert tuning.tuned_value("mxu_crossover") == 1024
    (event,) = _knob_changes("mxu_crossover")
    assert event["reason"] == "retune: autotune calibration"
    assert event["evidence"]["key"] == "mxu_crossover_faces"
    # the env pin beats the controller: actuation refused, pin read back
    monkeypatch.setenv("MESH_TPU_MXU_CROSSOVER_FACES", "65536")
    assert tuning.pinned("mxu_crossover")
    assert tuning.actuate("mxu_crossover", 2048, reason="t") is None
    assert tuning.tuned_value("mxu_crossover") is None
    assert tuning.get("mxu_crossover") == 65536


def test_autotune_retune_hooks_shape():
    from mesh_tpu.query.autotune import retune_hooks

    hooks = retune_hooks()
    assert set(hooks) == {"accel_min_faces", "mxu_crossover",
                          "stream_n_buffers"}
    # with no persisted calibration each hook declines (None), which
    # the controller treats as "don't churn"
    for fn in hooks.values():
        result = fn()
        assert result is None or (isinstance(result, tuple)
                                  and len(result) == 2)


# -- concurrency: the actuate/read hammer under the lock witness -------

def test_actuate_read_hammer_under_lock_witness(monkeypatch):
    """8 threads hammer the single write path while readers spin.  The
    witness pins doc/concurrency.md row 24: tuning._LOCK takes no other
    lock while held (_emit runs after it drops)."""
    lockwitness.reset()
    tuning_site = "mesh_tpu/utils/tuning.py:_LOCK"
    monkeypatch.setattr(
        tuning, "_LOCK", _WitnessedLock(threading.Lock(), tuning_site))
    registry = obs.REGISTRY
    monkeypatch.setattr(
        registry, "_lock",
        _WitnessedLock(registry._lock,
                       "mesh_tpu/obs/metrics.py:Registry._lock"))
    recorder = get_recorder()
    monkeypatch.setattr(
        recorder, "_lock",
        _WitnessedLock(threading.Lock(),
                       "mesh_tpu/obs/recorder.py:FlightRecorder._lock"))

    errors = []
    per_thread = 50

    def actuator(idx):
        try:
            for step in range(per_thread):
                # alternate so every call is a real change (no no-ops)
                tuning.actuate(
                    "coalesce_window_ms",
                    float((idx + step) % 2) + 1.0,
                    reason="hammer_%d" % idx)
        except Exception as exc:                 # pragma: no cover
            errors.append(exc)

    def reader():
        try:
            for _ in range(per_thread * 4):
                tuning.get("coalesce_window_ms")
                tuning.generation()
                tuning.history_tail(8)
                tuning.status()
        except Exception as exc:                 # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=actuator, args=(i,))
               for i in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    # every successful actuation is accounted for, exactly once
    gen = tuning.generation()
    assert gen == _counter("mesh_tpu_tuner_changes_total",
                           knob="coalesce_window_ms", action="set")
    assert len(tuning.history_tail(1000)) == min(64, gen)
    # the concurrency contract: no edge leaves the tuning lock
    out_edges = [edge for edge in lockwitness.edges()
                 if edge[0] == tuning_site]
    assert out_edges == []
    lockwitness.reset()


# -- the jax-free `mesh-tpu tune` CLI ----------------------------------

def _run_tune(*argv, **env_overrides):
    env_overrides.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ, **env_overrides)
    return subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "tune"] + list(argv),
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO)


def test_tune_status_cli(tmp_path):
    proc = _run_tune("status", "--json",
                     MESH_TPU_COALESCE_WINDOW_MS="7.5")
    assert proc.returncode == 0, proc.stderr
    status = json.loads(proc.stdout)
    rows = {r["knob"]: r for r in status["knobs"]}
    assert set(rows) == {"coalesce_window_ms", "accel_min_faces",
                         "mxu_crossover", "stream_n_buffers",
                         "serve_pre_trip", "shard_min_q",
                         "anim_refit_max_inflation"}
    assert rows["coalesce_window_ms"]["pinned"]
    assert rows["coalesce_window_ms"]["value"] == 7.5
    assert not rows["serve_pre_trip"]["pinned"]
    # human output mentions the pin provenance
    proc = _run_tune("status", MESH_TPU_COALESCE_WINDOW_MS="7.5")
    assert proc.returncode == 0
    assert "pinned by MESH_TPU_COALESCE_WINDOW_MS" in proc.stdout


def test_tune_history_end_to_end(tmp_path):
    """ISSUE-13 acceptance: an actuation in one process is visible to
    `mesh-tpu tune history` in another, via the incident dump."""
    incident_dir = os.environ["MESH_TPU_INCIDENT_DIR"]
    assert tuning.actuate("coalesce_window_ms", 3.0,
                          reason="e2e", evidence={"pressure": 0.0})
    path = FlightRecorder(capacity=16).trigger("tuner_e2e")
    assert path is not None
    proc = _run_tune("history", "--dir", incident_dir, "--json")
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["source"] == path
    (event,) = out["events"]
    assert event["knob"] == "coalesce_window_ms"
    assert event["after"] == 3.0 and event["reason"] == "e2e"
    # naming the incident file directly works too, and prints the trail
    proc = _run_tune("history", os.path.basename(path),
                     "--dir", incident_dir)
    assert proc.returncode == 0
    assert "coalesce_window_ms" in proc.stdout and "e2e" in proc.stdout


def test_tune_history_falls_back_to_live_then_empty(tmp_path):
    # no incidents on disk, fresh process: empty live history, rc 0
    proc = _run_tune("history", "--dir", str(tmp_path / "none"))
    assert proc.returncode == 0, proc.stderr
    assert "live process" in proc.stdout
    assert "no knob changes recorded" in proc.stdout


def test_tune_history_unreadable_source_exits_2(tmp_path):
    bad = tmp_path / "incident-0-bad-0.json"
    bad.write_text("{not json")
    proc = _run_tune("history", str(bad))
    assert proc.returncode == 2
    assert "unreadable" in proc.stderr


def test_tune_cli_works_with_backend_wedged(tmp_path):
    # the mid-incident contract (same bar as `incidents`/`slo`/`prof`):
    # `tune` never initializes a jax backend, so it must still answer
    # when the only configured platform is absent entirely
    proc = _run_tune("status", "--json", JAX_PLATFORMS="tpu")
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["knobs"]
    proc = _run_tune("history", "--dir", str(tmp_path / "none"),
                     JAX_PLATFORMS="tpu")
    assert proc.returncode == 0, proc.stderr
