"""Any-hit ray kernel correctness (interpret mode on the CPU test platform;
the same kernel runs compiled on TPU inside visibility_compute — see
tests/test_tpu_compiled.py)."""

import numpy as np

from mesh_tpu.query.pallas_ray import ray_any_hit_pallas
from mesh_tpu.query.ray import ray_triangle_hits
from mesh_tpu.query.visibility import (
    _visibility_kernel, _visibility_kernel_pallas,
)

from .fixtures import box, icosphere


def _xla_any_hit(origins, dirs, tri):
    t, hit = ray_triangle_hits(
        origins[:, None, :], dirs[:, None, :],
        tri[None, :, 0], tri[None, :, 1], tri[None, :, 2],
    )
    return np.asarray(np.any(np.asarray(hit & (t >= 0.0)), axis=-1))


class TestRayAnyHitPallas:
    def test_matches_xla_reduction(self):
        rng = np.random.RandomState(0)
        v, f = icosphere(2)
        tri = v[f].astype(np.float32)
        # rays from random points in a shell, random directions: a mix of
        # hits (inward) and misses (outward/tangent)
        origins = (rng.randn(300, 3) * 1.5).astype(np.float32)
        dirs = rng.randn(300, 3).astype(np.float32)
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        ref = _xla_any_hit(origins, dirs, tri)
        out = np.asarray(
            ray_any_hit_pallas(origins, dirs, tri, tile_q=32, tile_f=64,
                               interpret=True)
        )
        np.testing.assert_array_equal(out, ref)
        assert ref.any() and not ref.all()  # the case exercises both sides

    def test_ray_not_segment(self):
        # a hit far along the ray (t >> 1) must still block: the reference
        # casts CGAL Ray_3 to infinity (visibility.cpp:96-99)
        v, f = box(2.0)
        tri = v[f].astype(np.float32)
        origins = np.array([[0.0, 0.0, -50.0]], np.float32)
        dirs = np.array([[0.0, 0.0, 1.0]], np.float32)
        out = ray_any_hit_pallas(origins, dirs, tri, tile_q=8, tile_f=16,
                                 interpret=True)
        assert bool(np.asarray(out)[0])
        # and the opposite direction misses (t < 0 never blocks)
        out2 = ray_any_hit_pallas(origins, -dirs, tri, tile_q=8, tile_f=16,
                                  interpret=True)
        assert not bool(np.asarray(out2)[0])

    def test_segment_mode_t_bounds(self):
        # t in [0, 1]: a segment stopping short of the box must not hit
        v, f = box(2.0)
        tri = v[f].astype(np.float32)
        origins = np.array([[0.0, 0.0, -50.0]], np.float32)
        dirs = np.array([[0.0, 0.0, 10.0]], np.float32)   # reaches z=-40
        short = ray_any_hit_pallas(origins, dirs, tri, t_lo=0.0, t_hi=1.0,
                                   tile_q=8, tile_f=16, interpret=True)
        assert not bool(np.asarray(short)[0])
        dirs_far = np.array([[0.0, 0.0, 100.0]], np.float32)  # reaches z=50
        crossing = ray_any_hit_pallas(origins, dirs_far, tri, t_lo=0.0,
                                      t_hi=1.0, tile_q=8, tile_f=16,
                                      interpret=True)
        assert bool(np.asarray(crossing)[0])

    def test_nearest_alongnormal_matches_xla(self):
        from mesh_tpu.query.pallas_ray import nearest_alongnormal_pallas
        from mesh_tpu.query.ray import _nearest_alongnormal_xla

        rng = np.random.RandomState(2)
        v, f = icosphere(2)
        v32 = v.astype(np.float32)
        f32 = f.astype(np.int32)
        pts = (rng.randn(120, 3) * 1.2).astype(np.float32)
        # mix: radial normals (hit), random normals (hit/miss), plus a few
        # guaranteed misses far away pointing outward
        nrm = np.vstack([
            pts[:60] / np.linalg.norm(pts[:60], axis=1, keepdims=True),
            rng.randn(60, 3).astype(np.float32),
        ]).astype(np.float32)
        far = np.array([[50.0, 0, 0]], np.float32)
        pts = np.vstack([pts, far])
        nrm = np.vstack([nrm, np.array([[0.0, 1.0, 0.0]], np.float32)])
        d_x, f_x, p_x = _nearest_alongnormal_xla(v32, f32, pts, nrm)
        d_p, f_p, p_p = nearest_alongnormal_pallas(
            v32, f32, pts, nrm, tile_q=32, tile_f=64, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(d_p), np.asarray(d_x), atol=1e-5
        )
        assert not np.isfinite(np.asarray(d_p)[-1])    # the planted miss
        same = np.asarray(f_p) == np.asarray(f_x)
        np.testing.assert_allclose(
            np.asarray(p_p)[same], np.asarray(p_x)[same], atol=1e-5
        )

    def test_nearest_alongnormal_borderline_edge_hit_is_finite(self):
        # The winning hit lies exactly on a triangle edge (v == 0): the
        # kernel's division-free acceptance and a divided-form recompute
        # can disagree by ~1 ulp there.  Since the epilogue re-tests the
        # winner with the kernel's own acceptance, an in-kernel hit must
        # never come back as +inf (advisor round-2 finding, pallas_ray
        # recompute-miss).
        from mesh_tpu.query.pallas_ray import nearest_alongnormal_pallas

        v = np.array(
            [[0, 0, 0], [1, 0, 0], [0, 1, 0], [1, 1, 0]], np.float32
        )
        f = np.array([[0, 1, 2], [1, 3, 2]], np.int32)
        # queries exactly over the shared edge x+y=1 and over edge y=0
        pts = np.array(
            [[0.5, 0.5, -1.0], [0.3, 0.0, 2.0], [0.0, 0.0, -1.0]],
            np.float32,
        )
        nrm = np.array(
            [[0, 0, 1], [0, 0, -1], [0, 0, 1]], np.float32
        )
        d, face, p = nearest_alongnormal_pallas(
            v, f, pts, nrm, tile_q=8, tile_f=8, interpret=True
        )
        d = np.asarray(d)
        assert np.all(np.isfinite(d)), d
        np.testing.assert_allclose(d, [1.0, 2.0, 1.0], atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(p)[:, 2], [0.0, 0.0, 0.0], atol=1e-6
        )

    def test_tri_tri_matches_xla(self):
        from mesh_tpu.query.pallas_ray import tri_tri_any_hit_pallas
        from mesh_tpu.query.ray import _intersections_mask_xla

        v, f = icosphere(2)
        # query mesh: the same sphere shifted so the shells interpenetrate
        # on one side only -> a genuine mix of hits and misses
        qv = (v + np.array([1.2, 0.0, 0.0])).astype(np.float32)
        ref = np.asarray(
            _intersections_mask_xla(v.astype(np.float32), f, qv, f)
        )
        out = np.asarray(
            tri_tri_any_hit_pallas(
                qv[f], v.astype(np.float32)[f], tile_q=32, tile_f=64,
                interpret=True,
            )
        )
        np.testing.assert_array_equal(out, ref)
        assert ref.any() and not ref.all()

    def test_tri_tri_random_soup_matches_xla(self):
        from mesh_tpu.query.pallas_ray import tri_tri_any_hit_pallas
        from mesh_tpu.query.ray import _intersections_mask_xla

        rng = np.random.RandomState(7)
        v = rng.randn(60, 3).astype(np.float32)
        f = rng.randint(0, 60, size=(120, 3)).astype(np.int32)
        qv = (rng.randn(40, 3) * 0.8).astype(np.float32)
        qf = rng.randint(0, 40, size=(70, 3)).astype(np.int32)
        ref = np.asarray(_intersections_mask_xla(v, f, qv, qf))
        out = np.asarray(
            tri_tri_any_hit_pallas(qv[qf], v[f], tile_q=16, tile_f=32,
                                   interpret=True)
        )
        np.testing.assert_array_equal(out, ref)
        assert ref.any() and not ref.all()

    def test_self_intersection_count_matches_xla(self):
        from mesh_tpu.query.pallas_ray import self_intersection_count_pallas
        from mesh_tpu.query.ray import _self_intersection_count_xla

        # clean sphere: zero; sphere + one pierced face: the XLA oracle
        v, f = icosphere(2)
        v32, f32 = v.astype(np.float32), f.astype(np.int32)
        assert int(self_intersection_count_pallas(
            v32, f32, tile_q=32, tile_f=64, interpret=True)) == 0
        # graft a large triangle slicing through the sphere (no shared
        # vertices with the shell -> the slab and every face it crosses
        # count as involved)
        n0 = len(v32)
        v2 = np.vstack([v32, [[-2, -2, 0.1], [2, -2, 0.1], [0, 3, 0.1]]])
        f2 = np.vstack([f32, [[n0, n0 + 1, n0 + 2]]]).astype(np.int32)
        ref = int(_self_intersection_count_xla(v2, f2))
        out = int(self_intersection_count_pallas(
            v2, f2, tile_q=32, tile_f=64, interpret=True))
        assert out == ref
        assert ref > 0

    def test_visibility_pallas_path_matches_xla(self):
        import jax.numpy as jnp

        rng = np.random.RandomState(1)
        v, f = icosphere(2)
        v32 = jnp.asarray(v, jnp.float32)
        tri = v32[jnp.asarray(f)]
        cams = jnp.asarray([[3.0, 0.0, 0.0], [0.0, -2.5, 1.0]], jnp.float32)
        normals = jnp.asarray(
            v / np.linalg.norm(v, axis=1, keepdims=True), jnp.float32
        )
        sensors = jnp.asarray(
            np.tile(np.eye(3).reshape(-1), (2, 1)) * 2.0, jnp.float32
        )
        for sens in (None, sensors):
            vis_x, ndc_x = _visibility_kernel(
                v32, tri[:, 0], tri[:, 1], tri[:, 2], cams, normals, sens,
                jnp.float32(1e-3), chunk=64,
            )
            vis_p, ndc_p = _visibility_kernel_pallas(
                v32, tri, cams, normals, sens, jnp.float32(1e-3),
                interpret=True,
            )
            np.testing.assert_array_equal(np.asarray(vis_p), np.asarray(vis_x))
            np.testing.assert_allclose(
                np.asarray(ndc_p), np.asarray(ndc_x), atol=1e-6
            )
