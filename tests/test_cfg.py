"""CFG builder edge cases (mesh_tpu/analysis/cfg.py).

The flow-sensitive rule families (RES/LED/FLW) are only as sound as
the per-function CFG under them, so the tricky shapes get direct
graph-level tests here: ``continue`` inside a finally-protected loop,
``return``/``raise`` threading through ``finally`` bodies, exception-
swallowing ``with contextlib.suppress`` blocks, ``try/except/else/
finally`` routing, nested generators, and the None-guard edge
assumptions the path search prunes on.  Rule-level behaviour lives in
``tests/test_analysis.py``; this file is about edges and reachability.

Stdlib-only, jax-free, like the analyzer itself.
"""

import ast
import textwrap

from mesh_tpu.analysis.cfg import (
    build_cfg, cfg_for, may_raise, reset_stats, snapshot_stats,
)
from mesh_tpu.analysis.dataflow import (
    PARAM, ReachingDefs, find_path, reachable,
)


def _func(source, name=None):
    tree = ast.parse(textwrap.dedent(source))
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    if name is None:
        return funcs[0]
    return next(f for f in funcs if f.name == name)


def _cfg(source, name=None):
    return build_cfg(_func(source, name))


def _node(cfg, marker, source):
    """The stmt node on the (1-based) line containing ``marker``."""
    lines = textwrap.dedent(source).splitlines()
    lineno = next(i for i, text in enumerate(lines, 1) if marker in text)
    return next(n for n in cfg.stmt_nodes() if n.line == lineno)


def _succ_kinds(cfg, node):
    return {e.kind for e in cfg.succ[node]}


# -- finally threading --------------------------------------------------

CONTINUE_IN_FINALLY_LOOP = """
def f(items):
    for x in items:
        try:
            if x:
                continue
            work(x)
        finally:
            cleanup()
    done()
"""


def test_continue_routes_through_finally():
    cfg = _cfg(CONTINUE_IN_FINALLY_LOOP)
    cont = next(n for n in cfg.stmt_nodes()
                if isinstance(n.stmt, ast.Continue))
    header = _node(cfg, "for x", CONTINUE_IN_FINALLY_LOOP)
    cleanup = _node(cfg, "cleanup", CONTINUE_IN_FINALLY_LOOP)
    # the continue does NOT jump straight to the loop header — it must
    # run the finally body first
    assert not any(e.dst is header for e in cfg.succ[cont])
    (edge,) = cfg.succ[cont]
    assert edge.kind == "continue" and edge.dst.kind == "finally"
    # ... and the finally body's exit carries it back to the header
    assert any(e.dst is header and e.kind == "continue"
               for e in cfg.succ[cleanup])
    # the normal iteration also loops back through cleanup
    assert any(e.dst is header and e.kind == "back"
               for e in cfg.succ[cleanup])


RETURN_IN_TRY = """
def f(x):
    try:
        return work(x)
    finally:
        cleanup()
"""


def test_return_routes_through_finally():
    cfg = _cfg(RETURN_IN_TRY)
    ret = next(n for n in cfg.stmt_nodes()
               if isinstance(n.stmt, ast.Return))
    cleanup = _node(cfg, "cleanup", RETURN_IN_TRY)
    # no direct return -> exit edge; the finally interposes
    assert not any(e.dst is cfg.exit for e in cfg.succ[ret])
    assert any(e.dst.kind == "finally" and e.kind == "return"
               for e in cfg.succ[ret])
    assert any(e.dst is cfg.exit and e.kind == "return"
               for e in cfg.succ[cleanup])
    # work(x) may raise: that path ALSO runs the finally, then escapes
    assert any(e.dst is cfg.raise_exit and e.kind == "raise"
               for e in cfg.succ[cleanup])


TRY_EXCEPT_ELSE_FINALLY = """
def f(x):
    try:
        a = step(x)
    except ValueError:
        b = fallback()
    else:
        c = use(a)
    finally:
        d = teardown()
    return done(a)
"""


def test_try_except_else_finally_routing():
    cfg = _cfg(TRY_EXCEPT_ELSE_FINALLY)
    a = _node(cfg, "a = step", TRY_EXCEPT_ELSE_FINALLY)
    c = _node(cfg, "c = use", TRY_EXCEPT_ELSE_FINALLY)
    d = _node(cfg, "d = teardown", TRY_EXCEPT_ELSE_FINALLY)
    ret = _node(cfg, "return done", TRY_EXCEPT_ELSE_FINALLY)
    handler = next(n for n in cfg.nodes if n.kind == "handler")
    # the try body's raise edge lands on the handler...
    assert any(e.dst is handler and e.kind == "except"
               for e in cfg.succ[a])
    # ...but ValueError is not a catch-all, so the exception may also
    # pass the handler by: a routes onward through the finally too
    assert any(e.dst.kind == "finally" for e in cfg.succ[a])
    # the else body raising must NOT re-enter this try's own handler
    assert not any(e.dst is handler for e in cfg.succ[c])
    assert any(e.dst.kind == "finally" and e.kind == "finally"
               for e in cfg.succ[c])
    # every continuation funnels through d before the return
    assert any(e.dst is ret for e in cfg.succ[d])
    assert any(e.dst is cfg.raise_exit for e in cfg.succ[d])


BREAK_IN_FINALLY_LOOP = """
def f(items):
    while True:
        try:
            if probe(items):
                break
        finally:
            note(items)
    return items
"""


def test_break_routes_through_finally_and_while_true_has_no_false_exit():
    cfg = _cfg(BREAK_IN_FINALLY_LOOP)
    header = _node(cfg, "while True", BREAK_IN_FINALLY_LOOP)
    brk = next(n for n in cfg.stmt_nodes()
               if isinstance(n.stmt, ast.Break))
    note = _node(cfg, "note(", BREAK_IN_FINALLY_LOOP)
    ret = _node(cfg, "return items", BREAK_IN_FINALLY_LOOP)
    # while True never exits by its test
    assert "false" not in _succ_kinds(cfg, header)
    # the break reaches the return only via the finally body
    assert not any(e.dst is ret for e in cfg.succ[brk])
    assert any(e.dst.kind == "finally" and e.kind == "break"
               for e in cfg.succ[brk])
    assert any(e.dst is ret and e.kind == "break"
               for e in cfg.succ[note])


# -- exception swallowing ----------------------------------------------

SUPPRESS_WITH = """
import contextlib

def f(path):
    with contextlib.suppress(OSError):
        risky(path)
    after(path)
"""


def test_with_suppress_swallows_exception_edges():
    cfg = _cfg(SUPPRESS_WITH)
    risky = _node(cfg, "risky", SUPPRESS_WITH)
    after = _node(cfg, "after", SUPPRESS_WITH)
    # the may-raise edge from the body lands AFTER the with, not on
    # raise_exit: the suppress ate it
    assert any(e.dst is after and e.kind == "swallow"
               for e in cfg.succ[risky])
    assert not any(e.dst is cfg.raise_exit for e in cfg.succ[risky])


PLAIN_WITH = """
def f(lock, path):
    with lock:
        risky(path)
    after(path)
"""


def test_plain_with_does_not_swallow():
    cfg = _cfg(PLAIN_WITH)
    risky = _node(cfg, "risky", PLAIN_WITH)
    assert any(e.dst is cfg.raise_exit for e in cfg.succ[risky])


# -- generators and nested defs ----------------------------------------

NESTED_GENERATOR = """
def outer(xs):
    def gen(ys):
        for y in ys:
            try:
                yield y
            finally:
                note(y)
    return gen(xs)
"""


def test_nested_def_bodies_stay_out_of_the_outer_cfg():
    outer = _cfg(NESTED_GENERATOR, name="outer")
    # the nested def is one opaque node; its yield is not in outer's CFG
    assert not any(isinstance(getattr(n.stmt, "value", None), ast.Yield)
                   for n in outer.stmt_nodes())
    inner = _cfg(NESTED_GENERATOR, name="gen")
    yield_node = next(n for n in inner.stmt_nodes()
                      if isinstance(getattr(n.stmt, "value", None),
                                    ast.Yield))
    # a bare yield is a flow-through node: no raise edge (a GeneratorExit
    # edge per yield would drown the resource rules in noise)
    assert not any(e.dst is inner.raise_exit
                   for e in inner.succ[yield_node])
    # ... but the generator still threads its finally on the normal path
    assert any(e.dst.kind == "finally" or e.dst.line
               for e in inner.succ[yield_node])


def test_may_raise_semantics():
    (call,) = ast.parse("f(x)").body
    (plain,) = ast.parse("x = 1").body
    (sub,) = ast.parse("y = d[k]").body
    (ra,) = ast.parse("raise ValueError").body
    assert may_raise(call) and may_raise(sub) and may_raise(ra)
    assert not may_raise(plain)


# -- guard assumptions and path search ---------------------------------

NONE_GUARDED_CLOSE = """
def f(ledger):
    rec = ledger.open()
    if rec is not None:
        ledger.close(rec)
    return 1
"""


def test_none_guard_assumption_prunes_leak_paths():
    cfg = _cfg(NONE_GUARDED_CLOSE)
    opened = _node(cfg, "ledger.open", NONE_GUARDED_CLOSE)
    close = _node(cfg, "ledger.close", NONE_GUARDED_CLOSE)
    # unpruned: skipping the guard body reaches exit without the close
    assert find_path(cfg, opened, lambda n: n is cfg.exit,
                     avoid={close}) is not None
    # pruned on "rec is None" assumptions: the only close-free path
    # requires rec to BE None, i.e. nothing was opened — no leak
    assert find_path(cfg, opened, lambda n: n is cfg.exit,
                     avoid={close}, prune_none_of={"rec"}) is None


def test_reachable_and_edge_filter():
    src = """
    def f(flag):
        start()
        while flag:
            step()
        finish()
    """
    cfg = _cfg(src)
    start = _node(cfg, "start", src)
    step = _node(cfg, "step", src)
    finish = _node(cfg, "finish", src)
    assert reachable(cfg, start, lambda n: n is finish)
    # forbid loop entry: step becomes unreachable
    assert not reachable(cfg, start, lambda n: n is step,
                         edge_filter=lambda e: e.kind != "true")


# -- reaching definitions ----------------------------------------------

def test_reaching_defs_merge_at_join():
    src = """
    def f(flag, x):
        y = 1
        if flag:
            y = host(x)
        return y
    """
    cfg = _cfg(src)
    rd = ReachingDefs(cfg)
    ret = _node(cfg, "return y", src)
    env = rd.at(ret)
    # both definitions of y reach the join; x is still the parameter
    assert len(env["y"]) == 2
    assert env["x"] == frozenset([PARAM])


def test_reaching_defs_kill_on_rebind():
    src = """
    def f(x):
        y = device(x)
        y = 2
        return y
    """
    cfg = _cfg(src)
    rd = ReachingDefs(cfg)
    ret = _node(cfg, "return y", src)
    (only,) = rd.at(ret)["y"]
    assert only is not PARAM and only.stmt.value.value == 2


# -- cache discipline ---------------------------------------------------

def test_cfg_cache_identity_and_reset():
    fd = _func("def f():\n    return 1\n")
    reset_stats()
    assert cfg_for(fd) is cfg_for(fd)
    assert snapshot_stats()["cfg_builds"] == 1
    reset_stats()
    assert snapshot_stats()["cfg_builds"] == 0
    cfg_for(fd)
    assert snapshot_stats()["cfg_builds"] == 1
