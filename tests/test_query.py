"""Spatial-query kernel tests: exact closest point vs f64 brute-force oracle,
part codes, nearest-alongnormal, normal-weighted NN, intersections
(reference styles: tests/test_mesh.py:89-109, tests/test_aabb_n_tree.py)."""

import numpy as np
import pytest
import jax.numpy as jnp

from mesh_tpu.query import (
    closest_faces_and_points,
    closest_vertices_with_distance,
    nearest_alongnormal,
    nearest_normal_weighted,
    intersections_mask,
    self_intersection_count,
)
from mesh_tpu import Mesh

from .fixtures import box, cylinder, icosphere


def _oracle_closest(v, f, points):
    """f64 numpy closest-point-on-mesh oracle (Ericson, unvectorized)."""
    tri = v[f.astype(np.int64)]
    out_d = np.full(len(points), np.inf)
    out_p = np.zeros((len(points), 3))
    for qi, p in enumerate(points):
        for (a, b, c) in tri:
            ab, ac, ap = b - a, c - a, p - a
            d1, d2 = ab @ ap, ac @ ap
            bp = p - b
            d3, d4 = ab @ bp, ac @ bp
            cp = p - c
            d5, d6 = ab @ cp, ac @ cp
            if d1 <= 0 and d2 <= 0:
                q = a
            elif d3 >= 0 and d4 <= d3:
                q = b
            elif d6 >= 0 and d5 <= d6:
                q = c
            else:
                vc = d1 * d4 - d3 * d2
                vb = d5 * d2 - d1 * d6
                va = d3 * d6 - d5 * d4
                if vc <= 0 and d1 >= 0 and d3 <= 0:
                    q = a + ab * (d1 / (d1 - d3))
                elif vb <= 0 and d2 >= 0 and d6 <= 0:
                    q = a + ac * (d2 / (d2 - d6))
                elif va <= 0 and (d4 - d3) >= 0 and (d5 - d6) >= 0:
                    w = (d4 - d3) / ((d4 - d3) + (d5 - d6))
                    q = b + w * (c - b)
                else:
                    denom = 1.0 / (va + vb + vc)
                    q = a + ab * (vb * denom) + ac * (vc * denom)
            d = np.sum((p - q) ** 2)
            if d < out_d[qi]:
                out_d[qi] = d
                out_p[qi] = q
    return out_p, np.sqrt(out_d)


class TestClosestPoint:
    def test_vs_oracle_random(self):
        rng = np.random.RandomState(0)
        v = rng.rand(20, 3)
        f = rng.randint(0, 20, (10, 3)).astype(np.uint32)
        points = rng.rand(25, 3) * 2 - 0.5
        res = closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points.astype(np.float32)
        )
        oracle_p, oracle_d = _oracle_closest(v, f, points)
        got_d = np.linalg.norm(points - np.asarray(res["point"]), axis=1)
        # distances must match the exact oracle to 1e-5 (BASELINE parity bar)
        np.testing.assert_allclose(got_d, oracle_d, atol=1e-5)
        np.testing.assert_allclose(np.asarray(res["point"]), oracle_p, atol=1e-4)

    def test_part_codes_box(self):
        v, f = box(2.0)  # corners at +-1
        queries = np.array([
            [0.3, 0.2, -2.0],   # interior of a -z face
            [2.0, 2.0, 2.0],    # vertex corner (1,1,1)
            [0.0, -2.0, -2.0],  # edge between y=-1,z=-1
        ], dtype=np.float32)
        res = closest_faces_and_points(v.astype(np.float32), f.astype(np.int32), queries)
        part = np.asarray(res["part"])
        assert part[0] == 0          # interior
        assert part[1] in (4, 5, 6)  # some vertex code
        assert part[2] in (1, 2, 3)  # some edge code
        np.testing.assert_allclose(
            np.asarray(res["point"]),
            np.array([[0.3, 0.2, -1.0], [1, 1, 1], [0, -1, -1]]),
            atol=1e-6,
        )

    def test_closest_vertices(self):
        rng = np.random.RandomState(1)
        v = rng.randn(50, 3)
        q = rng.randn(30, 3)
        idx, dist = closest_vertices_with_distance(
            v.astype(np.float32), q.astype(np.float32)
        )
        d2 = np.linalg.norm(q[:, None] - v[None], axis=-1)
        np.testing.assert_array_equal(np.asarray(idx), d2.argmin(axis=1))
        np.testing.assert_allclose(np.asarray(dist), d2.min(axis=1), atol=1e-5)

    def test_batched_queries_large(self):
        """Chunking must not corrupt results at non-multiple sizes."""
        rng = np.random.RandomState(2)
        v, f = icosphere(2)
        q = rng.randn(1037, 3).astype(np.float32)
        res = closest_faces_and_points(v.astype(np.float32), f.astype(np.int32), q, chunk=256)
        # every closest point lies (approximately) on the unit sphere surface
        r = np.linalg.norm(np.asarray(res["point"]), axis=1)
        assert np.all(r < 1.01) and np.all(r > 0.9)


class TestNearestAlongNormal:
    def test_box_interior(self):
        v, f = box(2.0)
        # z = 0.25 so the +z wall (distance 0.75) strictly beats the -z wall
        p = np.array([[0.2, 0.3, 0.25]], np.float32)
        n = np.array([[0.0, 0.0, 1.0]], np.float32)
        dist, face, pt = nearest_alongnormal(
            v.astype(np.float32), f.astype(np.int32), p, n
        )
        np.testing.assert_allclose(np.asarray(dist), [0.75], atol=1e-6)
        np.testing.assert_allclose(np.asarray(pt), [[0.2, 0.3, 1.0]], atol=1e-6)

    def test_miss_gives_inf(self):
        v, f = box(2.0)
        p = np.array([[10.0, 10.0, 10.0]], np.float32)
        n = np.array([[0.0, 0.0, 1.0]], np.float32)
        dist, _, _ = nearest_alongnormal(v.astype(np.float32), f.astype(np.int32), p, n)
        assert not np.isfinite(np.asarray(dist))[0]

    def test_unnormalized_direction_distance(self):
        v, f = box(2.0)
        p = np.array([[0.0, 0.0, 0.0]], np.float32)
        n = np.array([[0.0, 0.0, 4.0]], np.float32)  # |n| = 4
        dist, _, pt = nearest_alongnormal(v.astype(np.float32), f.astype(np.int32), p, n)
        np.testing.assert_allclose(np.asarray(dist), [1.0], atol=1e-6)


class TestNormalWeighted:
    def _two_walls(self):
        # two parallel unit quads at z=0 (normal +z) and z=0.4 (normal -z)
        v = np.array([
            [0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0],
            [0, 0, 0.4], [1, 1, 0.4], [1, 0, 0.4], [0, 1, 0.4],
        ], np.float32)
        f = np.array([
            [0, 1, 2], [0, 2, 3],      # +z normals
            [4, 5, 6], [4, 7, 5],      # -z normals
        ], np.int32)
        return v, f

    def test_eps0_is_classic_nn(self):
        """reference tests/test_aabb_n_tree.py:27-39: eps=0 == euclidean NN."""
        v, f = self._two_walls()
        q = np.array([[0.5, 0.5, 0.15]], np.float32)  # nearer z=0 wall
        n = np.array([[0.0, 0.0, -1.0]], np.float32)
        face, point = nearest_normal_weighted(v, f, q, n, eps=0.0)
        assert int(np.asarray(face)[0]) in (0, 1)
        np.testing.assert_allclose(np.asarray(point)[0, 2], 0.0, atol=1e-6)

    def test_eps_flips_choice(self):
        """reference tests/test_aabb_n_tree.py:41-52: with a normal term the
        farther-but-normal-agreeing wall wins."""
        v, f = self._two_walls()
        q = np.array([[0.5, 0.5, 0.15]], np.float32)
        n = np.array([[0.0, 0.0, -1.0]], np.float32)  # agrees with z=0.4 wall
        face, point = nearest_normal_weighted(v, f, q, n, eps=0.5)
        assert int(np.asarray(face)[0]) in (2, 3)
        np.testing.assert_allclose(np.asarray(point)[0, 2], 0.4, atol=1e-6)


class TestIntersections:
    def test_crossing_triangles(self):
        v1 = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0]], np.float32)
        f1 = np.array([[0, 1, 2]], np.int32)
        # a triangle piercing the first one's plane
        qv = np.array([[0.2, 0.2, -0.5], [0.4, 0.2, 0.5], [0.2, 0.4, 0.5]], np.float32)
        qf = np.array([[0, 1, 2]], np.int32)
        mask = np.asarray(intersections_mask(v1, f1, qv, qf))
        assert mask.tolist() == [True]

    def test_disjoint(self):
        v1, f1 = box(1.0)
        v2, f2 = box(1.0, center=(5, 5, 5))
        mask = np.asarray(
            intersections_mask(v1.astype(np.float32), f1.astype(np.int32),
                               v2.astype(np.float32), f2.astype(np.int32))
        )
        assert not mask.any()

    def test_self_intersection_counts(self):
        v, f = box(1.0)
        assert int(self_intersection_count(v.astype(np.float32), f.astype(np.int32))) == 0
        # a mesh of two crossing triangles, disjoint vertex sets
        v2 = np.array([
            [0, 0, 0], [1, 0, 0], [0, 1, 0],
            [0.2, 0.2, -0.5], [0.4, 0.2, 0.5], [0.2, 0.4, 0.5],
        ], np.float32)
        f2 = np.array([[0, 1, 2], [3, 4, 5]], np.int32)
        # both faces are involved in an intersection -> count of 2 (the
        # reference counts involved FACES, not pairs: aabb_normals.cpp:203-205
        # asks per triangle whether the tree intersects it anywhere)
        assert int(self_intersection_count(v2, f2)) == 2

    def test_shared_vertex_pairs_excluded(self):
        v, f = cylinder(12)
        assert int(self_intersection_count(v.astype(np.float32), f.astype(np.int32))) == 0


class TestCulledClosestPoint:
    """Two-phase top-k culled path (query/culled.py) vs the exact kernel."""

    def _mesh_and_queries(self, subdiv=4, n_q=257, seed=3):
        """Icosphere (subdiv=4 -> 5120 faces) + near-surface queries — the
        scan-registration regime the cull targets.  (For a query at the
        sphere's *center* every triangle is equidistant, so no finite k can
        certify optimality; the auto path falls back to brute force there,
        covered by test_auto_fallback_is_exact_even_with_tiny_k.)"""
        v, f = icosphere(subdiv)
        rng = np.random.RandomState(seed)
        d = rng.randn(n_q, 3)
        d /= np.linalg.norm(d, axis=1, keepdims=True)
        r = 1.0 + rng.uniform(-0.15, 0.4, size=(n_q, 1))
        q = (d * r).astype(np.float32)
        return v.astype(np.float32), f.astype(np.int32), q

    def test_matches_brute_force(self):
        from mesh_tpu.query import (
            closest_faces_and_points_culled,
        )

        v, f, q = self._mesh_and_queries()
        exact = closest_faces_and_points(v, f, q)
        culled = closest_faces_and_points_culled(v, f, q, k=64)
        assert bool(np.asarray(culled["tight"]).all())
        np.testing.assert_allclose(
            np.asarray(culled["sqdist"]), np.asarray(exact["sqdist"]), atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(culled["point"]), np.asarray(exact["point"]), atol=1e-5
        )

    def test_auto_fallback_is_exact_even_with_tiny_k(self):
        from mesh_tpu.query import closest_faces_and_points_auto

        v, f, q = self._mesh_and_queries()
        exact = closest_faces_and_points(v, f, q)
        # force the culled path (threshold below F) with a starved candidate
        # set so some certificates fail and the brute-force fallback runs
        res = closest_faces_and_points_auto(
            v, f, q, brute_force_max_faces=1, k=2
        )
        np.testing.assert_allclose(
            res["sqdist"], np.asarray(exact["sqdist"]), atol=1e-6
        )
        np.testing.assert_allclose(
            res["point"], np.asarray(exact["point"]), atol=1e-5
        )

    def test_auto_small_mesh_uses_exact(self):
        from mesh_tpu.query import closest_faces_and_points_auto

        v, f = box(1.0)
        q = np.array([[2.0, 0.0, 0.0], [0.0, 0.0, 0.0]], np.float32)
        res = closest_faces_and_points_auto(
            v.astype(np.float32), f.astype(np.int32), q
        )
        np.testing.assert_allclose(np.sqrt(res["sqdist"][0]), 1.5, atol=1e-6)

    def test_part_codes_match(self):
        from mesh_tpu.query import closest_faces_and_points_culled

        v, f, q = self._mesh_and_queries(n_q=64)
        exact = closest_faces_and_points(v, f, q)
        culled = closest_faces_and_points_culled(v, f, q, k=64)
        same_face = np.asarray(culled["face"]) == np.asarray(exact["face"])
        # where the winning face agrees, the part code must agree too
        np.testing.assert_array_equal(
            np.asarray(culled["part"])[same_face],
            np.asarray(exact["part"])[same_face],
        )


class TestSearchTreeShapeParity:
    """Drop-in users of the reference get its exact return shapes
    (reference search.py:59-86: both closest-point trees return 1-D
    index and distance sequences of length Q)."""

    def test_closest_point_trees_return_flat_length_q(self):
        rng = np.random.RandomState(3)
        m = Mesh(v=rng.randn(20, 3), f=np.array([[0, 1, 2], [3, 4, 5]], np.uint32))
        queries = rng.randn(7, 3)
        for use_cgal in (False, True):
            idx, dist = m.compute_closest_point_tree(use_cgal).nearest(queries)
            idx, dist = np.asarray(idx), np.asarray(dist)
            assert idx.shape == (7,), (use_cgal, idx.shape)
            assert dist.shape == (7,), (use_cgal, dist.shape)
            # distances match the indexed vertices
            np.testing.assert_allclose(
                dist, np.linalg.norm(m.v[idx] - queries, axis=1), atol=1e-5
            )

    def test_closest_vertices_matches_both_backends(self):
        rng = np.random.RandomState(4)
        m = Mesh(v=rng.randn(30, 3), f=np.array([[0, 1, 2]], np.uint32))
        queries = rng.randn(9, 3)
        idx_a, _ = m.closest_vertices(queries)
        idx_b, _ = m.closest_vertices(queries, use_cgal=True)
        np.testing.assert_array_equal(np.asarray(idx_a), np.asarray(idx_b))


class TestDegenerateFaces:
    """Zero-area faces (duplicate or collinear corners) must report the
    exact segment distance on every path — the Voronoi region tests
    cancel to rounding noise there and previously picked an arbitrary
    region (found by hypothesis: a mesh whose true winner was a b==c
    face returned a 30% larger distance)."""

    def _meshes(self):
        # face 0: degenerate b==c segment from (0,0,0) to (1,0,0)
        # face 1: collinear corners spanning the same segment x in [0,2]
        # face 2: a genuine, distant triangle
        v = np.array(
            [[0, 0, 0], [1, 0, 0], [2, 0, 0],
             [10, 10, 10], [11, 10, 10], [10, 11, 10]], np.float32
        )
        f = np.array([[0, 1, 1], [0, 2, 1], [3, 4, 5]], np.int32)
        return v, f

    def test_xla_brute_segment_exact(self):
        v, f = self._meshes()
        pts = np.array(
            [[0.5, 0.3, 0.0],      # above the b==c segment interior
             [1.5, 0.0, 0.4],      # above the collinear span
             [-1.0, 0.0, 0.0]],    # beyond corner a
            np.float32,
        )
        res = closest_faces_and_points(v, f, pts, chunk=4)
        np.testing.assert_allclose(
            np.asarray(res["sqdist"]), [0.09, 0.16, 1.0], atol=1e-6
        )

    def test_pallas_interpret_matches(self):
        from mesh_tpu.query.pallas_closest import closest_point_pallas

        v, f = self._meshes()
        rng = np.random.RandomState(3)
        pts = np.vstack(
            [[[0.5, 0.3, 0.0], [1.5, 0.0, 0.4]],
             rng.randn(6, 3)]
        ).astype(np.float32)
        ref = closest_faces_and_points(v, f, pts, chunk=4)
        out = closest_point_pallas(v, f, pts, tile_q=8, tile_f=8,
                                   interpret=True)
        np.testing.assert_allclose(
            np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )

    def test_part_code_is_an_edge_on_degenerate_faces(self):
        v, f = self._meshes()
        res = closest_faces_and_points(
            v, f, np.array([[0.5, 0.3, 0.0]], np.float32), chunk=4
        )
        assert int(np.asarray(res["part"])[0]) in (1, 2, 3)
