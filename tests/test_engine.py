"""mesh_tpu.engine contract (doc/engine.md).

The acceptance bar for the engine PR: after warm-up, facade calls with
DISTINCT query counts inside one bucket reuse a cached plan (the retrace
counter stays flat) and return results bit-identical to the un-engined
path.  Also pins the coalescing executor (futures == sequential facade
calls), the MESH_TPU_NO_ENGINE bypass, warmup(), and the stats surface.
"""

import numpy as np
import pytest

from mesh_tpu import Mesh, engine
from mesh_tpu.batch import (
    batched_closest_faces_and_points,
    batched_vertex_normals,
    batched_vertex_visibility,
    fused_normals_and_closest_points,
)
from mesh_tpu.sphere import _icosphere


@pytest.fixture
def mesh():
    v, f = _icosphere(2)                    # 162 verts / 320 faces
    return Mesh(v=v, f=f)


@pytest.fixture
def meshes():
    rng = np.random.RandomState(3)
    v, f = _icosphere(2)
    return [Mesh(v=v + 0.01 * rng.randn(*v.shape), f=f) for _ in range(3)]


def _queries(q, seed=0):
    return np.asarray(np.random.RandomState(seed).randn(q, 3), np.float32)


def _direct(call, monkeypatch):
    """Run `call` with the engine bypassed (the pre-engine facade path)."""
    monkeypatch.setenv("MESH_TPU_NO_ENGINE", "1")
    try:
        return call()
    finally:
        monkeypatch.delenv("MESH_TPU_NO_ENGINE")


# ---------------------------------------------------------------------------
# planner: bucketing + plan reuse


def test_bucket_size_ladder():
    ladder = engine.Q_LADDER
    assert engine.bucket_size(1, ladder) == ladder[0]
    assert engine.bucket_size(ladder[0], ladder) == ladder[0]
    assert engine.bucket_size(ladder[0] + 1, ladder) == ladder[1]
    assert engine.bucket_size(ladder[-1], ladder) == ladder[-1]
    # beyond the top rung: next multiple of the top, never an error
    assert engine.bucket_size(ladder[-1] + 1, ladder) == 2 * ladder[-1]
    for bad in (0, -4):
        with pytest.raises(ValueError):
            engine.bucket_size(bad, ladder)


def test_plan_reuse_within_bucket_flat_retraces(mesh, monkeypatch):
    """The PR's acceptance test: after warm-up, 10 facade calls with
    distinct Q inside one bucket add ZERO plan-cache misses and match the
    direct path bit-for-bit."""
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    mesh.closest_faces_and_points(_queries(300))    # warm the 512-bucket
    engine.reset_stats()
    for i, q in enumerate(range(260, 510, 25)):     # 10 distinct Q, one bucket
        pts = _queries(q, seed=q)
        faces, points = mesh.closest_faces_and_points(pts)
        f_direct, p_direct = _direct(
            lambda: mesh.closest_faces_and_points(pts), monkeypatch)
        assert np.array_equal(faces, f_direct)
        assert np.array_equal(points, p_direct)
    snap = engine.stats()
    assert snap["retraces"] == 0
    assert snap["plan_cache"]["misses"] == 0
    assert snap["plan_cache"]["hits"] == 10
    assert 0.0 <= snap["pad_waste"] < 1.0


def test_crossing_a_bucket_boundary_compiles_once(mesh, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    mesh.closest_faces_and_points(_queries(40))     # warm the 64-bucket
    engine.reset_stats()
    mesh.closest_faces_and_points(_queries(50))     # same bucket: hit
    assert engine.stats()["plan_cache"]["misses"] == 0
    mesh.closest_faces_and_points(_queries(65))     # 128-bucket
    mesh.closest_faces_and_points(_queries(100))    # 128 again: hit
    snap = engine.stats()["plan_cache"]
    assert snap["misses"] <= 1 and snap["hits"] >= 2


def test_no_engine_bypass(mesh, monkeypatch):
    monkeypatch.setenv("MESH_TPU_NO_ENGINE", "1")
    assert not engine.engine_enabled()
    engine.reset_stats()
    pts = _queries(120)
    faces, points = mesh.closest_faces_and_points(pts)
    # the direct path must leave the engine completely untouched
    snap = engine.stats()
    assert snap["plan_cache"]["hits"] == 0
    assert snap["plan_cache"]["misses"] == 0
    assert faces.shape == (1, 120) and points.shape == (120, 3)
    monkeypatch.delenv("MESH_TPU_NO_ENGINE")
    assert engine.engine_enabled()


# ---------------------------------------------------------------------------
# batched entry points: engine path is bit-exact vs the direct path


def test_batched_closest_parity(meshes, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    pts = np.asarray(np.random.RandomState(5).randn(3, 77, 3), np.float32)
    f_eng, p_eng = batched_closest_faces_and_points(meshes, pts)
    f_dir, p_dir = _direct(
        lambda: batched_closest_faces_and_points(meshes, pts), monkeypatch)
    assert np.array_equal(np.asarray(f_eng), np.asarray(f_dir))
    assert np.array_equal(np.asarray(p_eng), np.asarray(p_dir))


def test_batched_normals_parity(meshes, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    n_eng = batched_vertex_normals(meshes)
    n_dir = _direct(lambda: batched_vertex_normals(meshes), monkeypatch)
    assert np.array_equal(np.asarray(n_eng), np.asarray(n_dir))


def test_fused_parity(meshes, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    pts = np.asarray(np.random.RandomState(7).randn(3, 55, 3), np.float32)
    eng = fused_normals_and_closest_points(meshes, pts)
    dire = _direct(
        lambda: fused_normals_and_closest_points(meshes, pts), monkeypatch)
    for a, b in zip(eng, dire):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_visibility_parity(meshes, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    cams = np.asarray([[0.0, 0.0, 3.0], [3.0, 0.0, 0.0]], np.float32)
    vis_eng = batched_vertex_visibility(meshes, cams)
    vis_dir = _direct(
        lambda: batched_vertex_visibility(meshes, cams), monkeypatch)
    assert np.array_equal(np.asarray(vis_eng), np.asarray(vis_dir))


# ---------------------------------------------------------------------------
# executor: coalesced futures == sequential facade calls


def test_executor_coalesces_same_topology(meshes, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    rng = np.random.RandomState(11)
    ptss = [np.asarray(rng.randn(q, 3), np.float32) for q in (150, 200, 90)]
    ex = engine.get_executor()
    engine.reset_stats()
    with ex.coalesce():
        futs = [
            ex.submit("closest_point", m, p) for m, p in zip(meshes, ptss)
        ]
    ex.drain()
    snap = engine.stats()["coalesced"]
    # all three share one topology -> ONE stacked dispatch
    assert snap["dispatches"] == 1
    assert snap["requests"] == 3 and snap["max_batch"] == 3
    for m, p, fut in zip(meshes, ptss, futs):
        faces, points = fut.result(timeout=60)
        f_seq, p_seq = m.closest_faces_and_points(p)
        assert np.array_equal(faces, f_seq)
        assert np.array_equal(points, p_seq)


def test_executor_fused_future(meshes, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    pts = _queries(130, seed=13)
    fut = engine.submit("fused", meshes[0], pts)
    normals, faces, points = fut.result(timeout=60)
    n_dir, f_dir, p_dir = _direct(
        lambda: fused_normals_and_closest_points([meshes[0]], pts[None]),
        monkeypatch)
    assert np.array_equal(normals, np.asarray(n_dir)[0])
    assert np.array_equal(faces, np.asarray(f_dir)[0])
    assert np.array_equal(points, np.asarray(p_dir)[0])


def test_executor_splits_mixed_topologies(mesh, meshes, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    small_v, small_f = _icosphere(1)
    other = Mesh(v=small_v, f=small_f)
    ex = engine.get_executor()
    engine.reset_stats()
    with ex.coalesce():
        f1 = ex.submit("closest_point", mesh, _queries(60, seed=1))
        f2 = ex.submit("closest_point", other, _queries(60, seed=2))
    ex.drain()
    # different topologies cannot stack: two dispatches
    assert engine.stats()["coalesced"]["dispatches"] == 2
    assert f1.result(timeout=60)[0].shape == (1, 60)
    assert f2.result(timeout=60)[0].shape == (1, 60)


def test_executor_rejects_bad_requests(mesh):
    ex = engine.get_executor()
    with pytest.raises(ValueError):
        ex.submit("no_such_op", mesh, _queries(10))
    with pytest.raises(ValueError):
        ex.submit("closest_point", mesh, np.zeros((0, 3), np.float32))


# ---------------------------------------------------------------------------
# executor lifecycle: shutdown hardening + deadline/cancel hooks
#
# These use FRESH EngineExecutor instances: the process-wide
# get_executor() is shared by every other test in the suite, and a
# shut-down singleton would poison them all.


def test_shutdown_submit_raises(mesh):
    ex = engine.EngineExecutor()
    ex.shutdown()
    with pytest.raises(engine.EngineShutdown):
        ex.submit("closest_point", mesh, _queries(10))


def test_shutdown_completes_queued_work(mesh, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    ex = engine.EngineExecutor()
    ex.hold()
    fut = ex.submit("closest_point", mesh, _queries(20, seed=21))
    ex.release()
    ex.shutdown()
    faces, points = fut.result(timeout=60)
    assert faces.shape == (1, 20) and points.shape == (20, 3)


def test_drain_after_shutdown_returns_immediately(mesh):
    from mesh_tpu.obs.clock import monotonic

    ex = engine.EngineExecutor()
    ex.shutdown()
    t0 = monotonic()
    ex.drain()
    assert monotonic() - t0 < 1.0
    # idempotent, still fast the second time
    ex.shutdown()
    ex.drain()


def test_queued_deadline_expiry_drops_request(mesh, monkeypatch):
    from mesh_tpu.errors import DeadlineExceeded
    from mesh_tpu.obs.clock import monotonic

    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    ex = engine.EngineExecutor()
    try:
        ex.hold()
        # already expired when the worker gets to it
        dead = ex.submit("closest_point", mesh, _queries(15, seed=31),
                         deadline=monotonic() - 0.001)
        live = ex.submit("closest_point", mesh, _queries(15, seed=32),
                         deadline=monotonic() + 60.0)
        ex.release()
        with pytest.raises(DeadlineExceeded):
            dead.result(timeout=60)
        faces, _ = live.result(timeout=60)
        assert faces.shape == (1, 15)
    finally:
        ex.shutdown()


def test_cancel_before_dispatch_skips_request(mesh, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    from mesh_tpu.obs.metrics import REGISTRY

    cancelled = REGISTRY.counter("mesh_tpu_engine_cancelled_total")
    before = cancelled.total()
    ex = engine.EngineExecutor()
    try:
        ex.hold()
        fut = ex.submit("closest_point", mesh, _queries(15, seed=41))
        assert fut.cancel()
        ex.release()
        ex.drain()
        assert fut.cancelled()
        assert cancelled.total() == before + 1
    finally:
        ex.shutdown()


# ---------------------------------------------------------------------------
# warmup + stats surface


def test_warmup_precompiles_then_hits(monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    shapes = ((162, 320),)
    engine.warmup(mesh_shapes=shapes, q_buckets=(256,), b_buckets=(1,),
                  ops=("closest_point",))
    # idempotent: everything is already in the plan cache
    assert engine.warmup(
        mesh_shapes=shapes, q_buckets=(256,), b_buckets=(1,),
        ops=("closest_point",)) == 0
    engine.reset_stats()
    v, f = _icosphere(2)
    Mesh(v=v, f=f).closest_faces_and_points(_queries(250))
    snap = engine.stats()["plan_cache"]
    assert snap["misses"] == 0 and snap["hits"] == 1


def test_stats_shape(mesh, monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    engine.reset_stats()
    mesh.closest_faces_and_points(_queries(33))
    snap = engine.stats()
    assert set(snap) == {
        "plan_cache", "retraces", "pad_waste", "coalesced",
        "dispatch_latency",
    }
    assert set(snap["plan_cache"]) == {
        "hits", "misses", "evictions", "compile_seconds",
    }
    assert snap["retraces"] == snap["plan_cache"]["misses"]
    lat = snap["dispatch_latency"]["closest_point"]
    assert lat["count"] == 1 and lat["mean_ms"] > 0
    engine.reset_stats()
    assert engine.stats()["plan_cache"]["hits"] == 0
