"""Released-file layout robustness for load_body_model_npz.

Real SMPL-family distributions are pickled chumpy models converted to
.npz with varying care; each test builds a synthetic file mimicking one
documented quirk (scipy-sparse J_regressor, chumpy object arrays, f64
payloads, key aliases, flattened shapedirs, MANO pose-PCA components)
and asserts the loaded model matches the clean round-trip bit-for-bit
where exact, or to f32 where a cast is involved."""

import numpy as np
import pytest

import jax.numpy as jnp

from mesh_tpu.models import (
    BodyModel,
    lbs,
    load_body_model_npz,
    mano_pose_from_pca,
    save_body_model_npz,
    synthetic_family_model,
)


class FakeCh:
    """Duck-typed chumpy.Ch: loader must use .r without importing chumpy."""

    def __init__(self, arr):
        self.r = np.asarray(arr)


@pytest.fixture(scope="module")
def clean(tmp_path_factory):
    model = synthetic_family_model("mano", seed=3)
    path = tmp_path_factory.mktemp("npz") / "clean.npz"
    save_body_model_npz(model, path)
    return model, dict(np.load(path, allow_pickle=True)), tmp_path_factory


def _roundtrip(clean, tmp_name, **overrides):
    model, raw, factory = clean
    data = dict(raw)
    data.update(overrides)
    path = factory.mktemp("npz") / (tmp_name + ".npz")
    np.savez(path, **data)
    return model, load_body_model_npz(path)


def _assert_same_weights(a, b, atol=0.0):
    for field in ("v_template", "shapedirs", "posedirs", "joint_regressor",
                  "lbs_weights"):
        np.testing.assert_allclose(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            atol=atol,
        )
    np.testing.assert_array_equal(np.asarray(a.faces), np.asarray(b.faces))
    assert a.parents == b.parents


def test_sparse_j_regressor(clean):
    scipy_sparse = pytest.importorskip("scipy.sparse")
    model, raw, _ = clean
    sparse = scipy_sparse.csc_matrix(np.asarray(model.joint_regressor))
    ref, loaded = _roundtrip(clean, "sparse", J_regressor=sparse)
    _assert_same_weights(ref, loaded)


def test_chumpy_object_arrays(clean):
    model, raw, _ = clean
    wrapped = {
        k: np.array(FakeCh(raw[k]), dtype=object)
        for k in ("v_template", "shapedirs", "posedirs", "weights")
    }
    ref, loaded = _roundtrip(clean, "chumpy", **wrapped)
    _assert_same_weights(ref, loaded)


def test_f64_payload_casts_to_f32(clean):
    model, raw, _ = clean
    f64 = {k: np.asarray(raw[k], np.float64)
           for k in ("v_template", "shapedirs", "posedirs", "J_regressor",
                     "weights")}
    ref, loaded = _roundtrip(clean, "f64", **f64)
    assert loaded.v_template.dtype == jnp.float32
    _assert_same_weights(ref, loaded, atol=1e-7)


def test_key_aliases_faces_and_lbs_weights(clean):
    model, raw, factory = clean
    data = dict(raw)
    data["faces"] = data.pop("f")
    data["lbs_weights"] = data.pop("weights")
    path = factory.mktemp("npz") / "alias.npz"
    np.savez(path, **data)
    loaded = load_body_model_npz(path)
    _assert_same_weights(model, loaded)


def test_flattened_shapedirs(clean):
    model, raw, _ = clean
    flat = np.asarray(raw["shapedirs"])
    flat = flat.reshape(-1, flat.shape[-1])      # (V*3, B) export quirk
    ref, loaded = _roundtrip(clean, "flatshape", shapedirs=flat)
    _assert_same_weights(ref, loaded)


def test_uint32_root_sentinel(clean):
    # save_body_model_npz writes the official 2**32-1 root marker; the
    # loader must map it back to parents[0] == -1
    model, raw, _ = clean
    assert raw["kintree_table"][0, 0] == 2 ** 32 - 1
    ref, loaded = _roundtrip(clean, "sentinel")
    assert loaded.parents[0] == -1


def test_missing_key_reports_aliases(clean):
    model, raw, factory = clean
    data = dict(raw)
    del data["J_regressor"]
    path = factory.mktemp("npz") / "missing.npz"
    np.savez(path, **data)
    with pytest.raises(KeyError, match="J_regressor.*file keys"):
        load_body_model_npz(path)


def test_extra_keys_ignored(clean):
    ref, loaded = _roundtrip(
        clean, "extras", J_shaped=np.zeros(3), bs_style=np.array(b"lbs")
    )
    _assert_same_weights(ref, loaded)


class TestManoPosePCA:
    def _mano_file(self, clean, ncomp_stored=45):
        model, raw, factory = clean
        rng = np.random.RandomState(0)
        n_pose = np.asarray(raw["posedirs"]).reshape(
            raw["posedirs"].shape[0], 3, -1
        ).shape[-1] // 9 * 3   # (J-1)*3 axis-angle dims
        comps = rng.randn(ncomp_stored, n_pose)
        mean = 0.1 * rng.randn(n_pose)
        path = factory.mktemp("npz") / "mano.npz"
        np.savez(path, **dict(raw), hands_components=comps, hands_mean=mean)
        return load_body_model_npz(path), comps, mean

    def test_pca_basis_kept_on_model(self, clean):
        loaded, comps, mean = self._mano_file(clean)
        np.testing.assert_allclose(
            np.asarray(loaded.hands_components), comps, atol=1e-6
        )
        np.testing.assert_allclose(np.asarray(loaded.hands_mean), mean,
                                   atol=1e-6)

    def test_reduced_components_pose(self, clean):
        # the official mano package's ncomps: callers pass n <= 45 coeffs
        loaded, comps, mean = self._mano_file(clean)
        coeffs = np.array([0.5, -1.0, 0.25], np.float32)
        pose = np.asarray(mano_pose_from_pca(loaded, coeffs))
        assert pose.shape == (loaded.num_joints, 3)
        np.testing.assert_allclose(pose[0], 0.0)
        expect = (coeffs @ comps[:3] + mean).reshape(-1, 3)
        np.testing.assert_allclose(pose[1:], expect, atol=1e-5)
        # and the pose drives the forward pass
        verts, joints = lbs(
            loaded, np.zeros(loaded.num_betas, np.float32), pose
        )
        assert np.isfinite(np.asarray(verts)).all()

    def test_flat_hand_mean(self, clean):
        loaded, comps, mean = self._mano_file(clean)
        coeffs = np.ones(2, np.float32)
        with_mean = np.asarray(mano_pose_from_pca(loaded, coeffs))
        flat = np.asarray(mano_pose_from_pca(loaded, coeffs,
                                             flat_hand_mean=True))
        np.testing.assert_allclose(
            (with_mean - flat)[1:].reshape(-1), mean, atol=1e-5
        )

    def test_pca_basis_roundtrips_through_save(self, clean, tmp_path):
        loaded, comps, mean = self._mano_file(clean)
        save_body_model_npz(loaded, tmp_path / "rt.npz")
        again = load_body_model_npz(tmp_path / "rt.npz")
        np.testing.assert_allclose(
            np.asarray(again.hands_components), comps, atol=1e-6
        )
        np.testing.assert_allclose(np.asarray(again.hands_mean), mean,
                                   atol=1e-6)

    def test_no_basis_raises(self, clean):
        model, _, _ = clean
        with pytest.raises(ValueError, match="hands_components"):
            mano_pose_from_pca(model, np.zeros(3))
