"""Parity tests on the reference's own aabb_normals unittest fixtures.

The vertex/face literals below are DATA extracted from the reference's
unittest geometry (reference data/unittest/{test_doublebox, cylinder,
cylinder_trans, self_intersecting_cyl}.obj — tiny Blender-exported
meshes), embedded here the same way test_reference_goldens.py embeds the
reference's golden output values.  The assertions mirror reference
tests/test_aabb_n_tree.py:29-89 exactly, so a pass is direct semantic
parity with the CGAL aabb_normals extension (aabb_normals.cpp:192-207,
AABB_n_tree.h:95-117):

- nearest with eps=0 is the classic euclidean NN; with eps>0 the blended
  ``|p-q| + eps*(1 - n.n_tri)`` metric changes the winners;
- the translated-cylinder coverage counts (<= 4 unique winners without
  normals, >= F-4 with);
- aabbtree_n_selfintersects counts the FACES involved in at least one
  non-vertex-sharing intersection (aabb_normals.cpp:203-205 asks per
  triangle whether the tree intersects it anywhere — NOT a pair count;
  the bent cylinder has 20 unordered intersecting pairs but only 2*8
  involved faces): 0 for the shared-face double box, exactly 2*8 for
  the bent (self-intersecting) cylinder.
"""

import numpy as np
import pytest

from mesh_tpu.geometry.compat import NormalizeRows, TriToScaledNormal
from mesh_tpu.query import self_intersection_count
from mesh_tpu.search import AabbNormalsTree


class _M:
    def __init__(self, v, f):
        self.v = np.asarray(v, np.float64)
        self.f = np.asarray(f, np.int32)


# reference data/unittest/test_doublebox.obj: two unit boxes stacked in z,
# sharing the 4 verts of the z=0.5 plane (the shared face is not meshed)
DOUBLEBOX_V = np.array([
    [0.5, 0.5, 0.5], [-0.5, 0.5, 0.5], [0.5, -0.5, 0.5], [-0.5, -0.5, 0.5],
    [0.5, 0.5, -0.5], [-0.5, 0.5, -0.5], [0.5, -0.5, -0.5],
    [-0.5, -0.5, -0.5],
    [0.5, 0.5, 1.5], [-0.5, 0.5, 1.5], [0.5, -0.5, 1.5], [-0.5, -0.5, 1.5],
])
DOUBLEBOX_F = np.array([
    [0, 2, 4], [6, 4, 2], [0, 4, 1], [5, 1, 4], [7, 5, 6], [4, 6, 5],
    [7, 6, 3], [2, 3, 6], [7, 3, 5], [1, 5, 3], [8, 9, 10], [11, 10, 9],
    [8, 10, 0], [2, 0, 10], [8, 0, 9], [1, 9, 0], [3, 2, 11], [10, 11, 2],
    [3, 11, 1], [9, 1, 11],
])

# reference data/unittest/cylinder.obj: open 8-segment cylinder, axis y
CYL_V = np.array([
    [0.0, -1.0, -1.0], [0.0, -1.0, 1.0], [-0.382683, -1.0, 0.923880],
    [-0.707107, -1.0, 0.707107], [-0.923880, -1.0, 0.382684],
    [-1.0, -1.0, -0.0], [-0.923879, -1.0, -0.382684],
    [-0.707107, -1.0, -0.707107], [-0.382683, -1.0, -0.923880],
    [1e-06, 1.0, -1.0], [-2e-06, 1.0, 1.0], [-0.382685, 1.0, 0.923879],
    [-0.707108, 1.0, 0.707105], [-0.923880, 1.0, 0.382681],
    [-1.0, 1.0, -3e-06], [-0.923878, 1.0, -0.382686],
    [-0.707105, 1.0, -0.707109], [-0.382681, 1.0, -0.923881],
])
CYL_F = np.array([
    [9, 0, 17], [0, 8, 17], [7, 16, 8], [16, 17, 8], [6, 15, 7],
    [15, 16, 7], [5, 14, 6], [14, 15, 6], [4, 13, 5], [13, 14, 5],
    [3, 12, 4], [12, 13, 4], [2, 11, 3], [11, 12, 3], [1, 10, 2],
    [10, 11, 2],
])

# reference data/unittest/cylinder_trans.obj: the same half-cylinder shell
# translated so it faces the original across a gap
CYL_TRANS_V = np.array([
    [1.057678, -1.0, -1.0], [1.057678, -1.0, 1.0],
    [0.674994, -1.0, 0.923880], [0.350571, -1.0, 0.707107],
    [0.133798, -1.0, 0.382684], [0.057678, -1.0, -0.0],
    [0.133798, -1.0, -0.382684], [0.350571, -1.0, -0.707107],
    [0.674995, -1.0, -0.923880], [1.057678, 1.0, -1.0],
    [1.057676, 1.0, 1.0], [0.674992, 1.0, 0.923879],
    [0.350569, 1.0, 0.707105], [0.133797, 1.0, 0.382681],
    [0.057678, 1.0, -3e-06], [0.133799, 1.0, -0.382686],
    [0.350573, 1.0, -0.707109], [0.674997, 1.0, -0.923881],
])
CYL_TRANS_F = CYL_F.copy()

# reference data/unittest/self_intersecting_cyl.obj: an 8-segment cylinder
# whose bottom cap apex (vertex 17) is pushed below the rim, bending the
# cap fan through the side wall: 8 genuine crossings
SELF_INT_CYL_V = np.array([
    [0.0, -0.5, -1.0], [0.707107, -0.5, -0.707107], [1.0, -0.5, 0.0],
    [0.707107, -0.5, 0.707107], [-0.0, -0.5, 1.0],
    [-0.707107, -0.5, 0.707107], [-1.0, -0.5, -0.0],
    [-0.707107, -0.5, -0.707107], [-0.0, 0.5, -1.0],
    [0.707106, 0.5, -0.707107], [1.0, 0.5, -1e-06],
    [0.707107, 0.5, 0.707107], [-0.0, 0.5, 1.0],
    [-0.707107, 0.5, 0.707107], [-1.0, 0.5, -1e-06],
    [-0.707106, 0.5, -0.707107], [0.0, -0.5, 0.0], [0.0, -0.835754, 0.0],
])
SELF_INT_CYL_F = np.array([
    [16, 0, 1], [17, 9, 8], [16, 1, 2], [17, 10, 9], [16, 2, 3],
    [17, 11, 10], [16, 3, 4], [17, 12, 11], [16, 4, 5], [17, 13, 12],
    [16, 5, 6], [17, 14, 13], [16, 6, 7], [17, 15, 14], [7, 0, 16],
    [17, 8, 15], [0, 8, 9], [1, 9, 10], [2, 10, 11], [3, 11, 12],
    [4, 12, 13], [5, 13, 14], [6, 14, 15], [8, 0, 7],
])


class TestAabbNormalsFixtureParity:
    """reference tests/test_aabb_n_tree.py on the same geometry."""

    def test_dist_classic(self):
        # eps=0 is the classic euclidean NN (test_aabb_n_tree.py:29-39)
        tree = AabbNormalsTree(_M(DOUBLEBOX_V, DOUBLEBOX_F), eps=0.0)
        query_v = np.array([[0.5, 0.1, 0.25], [0.5, 0.1, 0.25]])
        query_n = np.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
        closest_tri, closest_p = tree.nearest(query_v, query_n)
        assert (closest_tri == np.array([[0], [0]])).all()
        np.testing.assert_allclose(closest_p, query_v, atol=1e-6)

    def test_dist_normals(self):
        # eps=0.5 pulls query 1 (normal +y) to the top face
        # (test_aabb_n_tree.py:41-52)
        tree = AabbNormalsTree(_M(DOUBLEBOX_V, DOUBLEBOX_F), eps=0.5)
        query_v = np.array([[0.5, 0.1, 0.25], [0.5, 0.1, 0.25]])
        query_n = np.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
        closest_tri, closest_p = tree.nearest(query_v, query_n)
        assert (closest_tri == np.array([[2], [0]])).all()
        np.testing.assert_allclose(
            closest_p, np.array([[0.5, 0.5, 0.25], [0.5, 0.1, 0.25]]),
            atol=1e-6,
        )

    def test_cylinders_coverage(self):
        # facing half-cylinders (test_aabb_n_tree.py:54-76): without the
        # normal term every winner is at the two extremes (<= 4 unique
        # faces); with eps=10 nearly every face is someone's winner
        query_v = CYL_TRANS_V
        tri_n = NormalizeRows(TriToScaledNormal(CYL_TRANS_V, CYL_TRANS_F))
        query_n = np.zeros(CYL_TRANS_V.shape)
        for i_f in range(CYL_TRANS_F.shape[0]):
            query_n[CYL_TRANS_F[i_f, :], :] += tri_n[i_f, :]
        query_n = NormalizeRows(query_n)

        cyl = _M(CYL_V, CYL_F)
        closest_tri, _ = AabbNormalsTree(cyl, eps=0).nearest(query_v, query_n)
        assert np.unique(closest_tri).shape[0] <= 4

        closest_tri_n, _ = AabbNormalsTree(cyl, eps=10).nearest(
            query_v, query_n
        )
        assert np.unique(closest_tri_n).shape[0] >= CYL_F.shape[0] - 4

    def test_selfintersects_doublebox_is_zero(self):
        # every touching face pair of the two boxes shares a vertex, and
        # vertex-sharing pairs are excluded (test_aabb_n_tree.py:78-83)
        count = int(self_intersection_count(
            DOUBLEBOX_V.astype(np.float32), DOUBLEBOX_F.astype(np.int32)
        ))
        assert count == 0

    def test_selfintersects_bent_cylinder_is_2x8(self):
        # the bent lower fan crosses the cap fan: 8 faces on each side are
        # involved (in 20 unordered pairs — involvement, not pairs, is
        # what's counted; test_aabb_n_tree.py:85-89)
        count = int(self_intersection_count(
            SELF_INT_CYL_V.astype(np.float32),
            SELF_INT_CYL_F.astype(np.int32),
        ))
        assert count == 2 * 8


# all-pairs interpret-mode Pallas over full fixtures: ~10 min per test on
# a 1-core CPU host, so tier-1 (-m 'not slow') defers these to the full
# suite; the same tiles' exactness stays covered in tier-1 by the smaller
# moller/pallas_ray batteries
@pytest.mark.slow
class TestSelfIntersectKernelAlgorithms:
    """Both Pallas self-intersection tiles (segment / Möller interval)
    must reproduce the reference fixture counts — the gate that lets the
    facade pick the ~2x-cheaper Möller tile on clean meshes without
    changing any reference-visible number."""

    def _counts(self, v, f):
        from mesh_tpu.query.pallas_closest import mesh_is_nondegenerate
        from mesh_tpu.query.pallas_ray import self_intersection_count_pallas

        v = v.astype(np.float32)
        f = f.astype(np.int32)
        assert mesh_is_nondegenerate(v, f), (
            "fixture grew a degenerate face — the production gate would "
            "route it to the segment tile; update this test's premise"
        )
        return {
            algo: int(self_intersection_count_pallas(
                v, f, tile_q=32, tile_f=64, interpret=True,
                algorithm=algo))
            for algo in ("segment", "moller")
        }

    def test_doublebox_both_algorithms(self):
        counts = self._counts(DOUBLEBOX_V, DOUBLEBOX_F)
        assert counts == {"segment": 0, "moller": 0}

    def test_bent_cylinder_both_algorithms(self):
        counts = self._counts(SELF_INT_CYL_V, SELF_INT_CYL_F)
        assert counts == {"segment": 2 * 8, "moller": 2 * 8}

    def test_translated_cylinder_both_algorithms(self):
        counts = self._counts(CYL_V, CYL_F)
        assert counts["segment"] == counts["moller"]
