"""Unit contract of the multi-host query helpers (parallel/distributed.py).

The real-process SMPL-scale path runs in test_multihost.py; these pin the
host-side math and the loud-failure contract without spawning processes.
"""

import numpy as np
import pytest

import jax

from mesh_tpu.parallel import distributed


class _Dev:
    def __init__(self, process_index):
        self.process_index = process_index


class _FakeMesh:
    def __init__(self, proc_order):
        self.devices = np.array([_Dev(p) for p in proc_order], dtype=object)


def test_misordered_mesh_fails_loudly():
    # a mesh whose device order interleaves processes would return rows in
    # the wrong order — must raise, not silently misorder
    with pytest.raises(ValueError, match="process order"):
        distributed._process_blocks(_FakeMesh([0, 1, 0, 1]), 8, 2)


def test_single_process_blocks():
    counts, blocks, rpd = distributed._process_blocks(_FakeMesh([0, 0]), 7, 2)
    assert list(counts) == [7]
    assert rpd == 4 and list(blocks) == [8]


def test_ragged_counts_across_processes(monkeypatch):
    # two processes, 4 local devices each, ragged counts 6000/4100:
    # rows_per_device is the max ceil(n/ld) and every block pads to it
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda x, **kw: np.array([[6000, 4], [4100, 4]], np.int64))
    counts, blocks, rpd = distributed._process_blocks(
        _FakeMesh([0, 0, 0, 0, 1, 1, 1, 1]), 6000, 4)
    assert list(counts) == [6000, 4100]
    assert rpd == 1500
    assert list(blocks) == [6000, 6000]
    # the trim mask the facade builds from these keeps exactly the real rows
    keep = np.concatenate([
        (np.arange(block) < n).astype(bool)
        for n, block in zip(counts, blocks)
    ])
    assert keep.sum() == 10100 and keep.size == 12000


def test_zero_row_process(monkeypatch):
    # a host with no points still participates (pads a full empty block)
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda x, **kw: np.array([[0, 4], [8, 4]], np.int64))
    counts, blocks, rpd = distributed._process_blocks(
        _FakeMesh([0, 0, 0, 0, 1, 1, 1, 1]), 0, 4)
    assert list(counts) == [0, 8]
    assert rpd == 2 and list(blocks) == [8, 8]
