"""The assume_nondegenerate fast path (VERDICT r3 weak #3).

closest_point_pallas(assume_nondegenerate=True) compiles the tile without
its degenerate-face override (~25% fewer VPU ops).  Contract: on a mesh
where every face clears the relative area cut, the variant is
BIT-IDENTICAL to the default (the dropped `where` is the identity there);
`mesh_is_nondegenerate` is the staging check that licenses the flag, and
the facades derive it from data rather than assuming it.
"""

import numpy as np

from mesh_tpu.query.pallas_closest import (
    closest_point_pallas,
    mesh_is_nondegenerate,
)
from mesh_tpu.sphere import _icosphere


def _sphere():
    v, f = _icosphere(3)
    return v.astype(np.float32), f.astype(np.int32)


def test_mesh_is_nondegenerate_detects():
    v, f = _sphere()
    assert mesh_is_nondegenerate(v, f)

    # inject a collinear (zero-area) face
    f_bad = np.vstack([f, [[0, 1, 1]]]).astype(np.int32)
    assert not mesh_is_nondegenerate(v, f_bad)

    # a sliver 1e-6 of the area cut fails; margin keeps honest faces in
    v_sliver = np.array(
        [[0, 0, 0], [1, 0, 0], [0.5, 1e-9, 0]], np.float64)
    assert not mesh_is_nondegenerate(v_sliver, [[0, 1, 2]])


def test_mesh_is_nondegenerate_batched():
    v, f = _sphere()
    batch = np.stack([v, v * 2.0])
    assert mesh_is_nondegenerate(batch, f)
    # collapse one face of one mesh in the batch -> whole batch fails
    bad = batch.copy()
    bad[1, f[0, 2]] = bad[1, f[0, 1]]
    assert not mesh_is_nondegenerate(bad, f)


def test_flag_is_bit_identical_on_clean_mesh():
    v, f = _sphere()
    rng = np.random.RandomState(0)
    pts = rng.randn(500, 3).astype(np.float32)
    base = closest_point_pallas(v, f, pts, tile_q=64, tile_f=256,
                                interpret=True)
    fast = closest_point_pallas(v, f, pts, tile_q=64, tile_f=256,
                                interpret=True, assume_nondegenerate=True)
    np.testing.assert_array_equal(np.asarray(base["face"]),
                                  np.asarray(fast["face"]))
    np.testing.assert_array_equal(np.asarray(base["sqdist"]),
                                  np.asarray(fast["sqdist"]))
    np.testing.assert_array_equal(np.asarray(base["point"]),
                                  np.asarray(fast["point"]))
    np.testing.assert_array_equal(np.asarray(base["part"]),
                                  np.asarray(fast["part"]))


def test_safe_tiles_escape_hatch(monkeypatch):
    # MESH_TPU_SAFE_TILES pins every facade to the safe tile variants:
    # the staging check reports False regardless of geometry (and must
    # not poison the content cache for later un-hatched calls)
    v, f = _sphere()
    monkeypatch.setenv("MESH_TPU_SAFE_TILES", "1")
    assert not mesh_is_nondegenerate(v, f)
    monkeypatch.delenv("MESH_TPU_SAFE_TILES")
    assert mesh_is_nondegenerate(v, f)


def test_culled_flag_is_bit_identical_on_clean_mesh():
    from mesh_tpu.query.pallas_culled import closest_point_pallas_culled

    v, f = _sphere()
    rng = np.random.RandomState(4)
    pts = rng.randn(300, 3).astype(np.float32)
    base = closest_point_pallas_culled(v, f, pts, tile_q=64, tile_f=128,
                                       interpret=True)
    fast = closest_point_pallas_culled(v, f, pts, tile_q=64, tile_f=128,
                                       interpret=True,
                                       assume_nondegenerate=True)
    for key in ("face", "sqdist", "point", "part"):
        np.testing.assert_array_equal(np.asarray(base[key]),
                                      np.asarray(fast[key]))


def test_mxu_flag_is_bit_identical_on_clean_mesh():
    from mesh_tpu.query.pallas_closest import closest_point_pallas_mxu

    v, f = _sphere()
    rng = np.random.RandomState(5)
    pts = rng.randn(200, 3).astype(np.float32)
    base = closest_point_pallas_mxu(v, f, pts, tile_q=64, tile_f=128,
                                    interpret=True)
    fast = closest_point_pallas_mxu(v, f, pts, tile_q=64, tile_f=128,
                                    interpret=True,
                                    assume_nondegenerate=True)
    for key in ("face", "sqdist", "point", "part"):
        np.testing.assert_array_equal(np.asarray(base[key]),
                                      np.asarray(fast[key]))


def test_normal_weighted_flag_is_bit_identical_on_clean_mesh():
    from mesh_tpu.query.pallas_normal_weighted import (
        nearest_normal_weighted_pallas,
    )

    v, f = _sphere()
    rng = np.random.RandomState(6)
    pts = rng.randn(150, 3).astype(np.float32)
    nrm = rng.randn(150, 3).astype(np.float32)
    base = nearest_normal_weighted_pallas(
        v, f, pts, nrm, eps=0.1, tile_q=64, tile_f=128, interpret=True)
    fast = nearest_normal_weighted_pallas(
        v, f, pts, nrm, eps=0.1, tile_q=64, tile_f=128, interpret=True,
        assume_nondegenerate=True)
    np.testing.assert_array_equal(np.asarray(base[0]), np.asarray(fast[0]))
    np.testing.assert_array_equal(np.asarray(base[1]), np.asarray(fast[1]))


def test_flag_reported_distance_still_exact_with_degenerates():
    # with the flag WRONGLY set on a degenerate mesh, the winner may be a
    # different face, but the epilogue still reports the winner's exact
    # distance — never garbage values
    v, f = _sphere()
    f_bad = np.vstack([f, [[0, 1, 1]], [[5, 5, 5]]]).astype(np.int32)
    rng = np.random.RandomState(1)
    pts = rng.randn(200, 3).astype(np.float32)
    res = closest_point_pallas(v, f_bad, pts, tile_q=64, tile_f=256,
                               interpret=True, assume_nondegenerate=True)
    sqd = np.asarray(res["sqdist"])
    assert np.all(np.isfinite(sqd)) and np.all(sqd >= 0)
    # every reported distance equals the true distance to the reported face
    from mesh_tpu.query.point_triangle import closest_point_on_triangle

    tri = v[f_bad[np.asarray(res["face"])]]
    _, true_sqd, _ = closest_point_on_triangle(
        pts, tri[:, 0], tri[:, 1], tri[:, 2])
    np.testing.assert_allclose(sqd, np.asarray(true_sqd), atol=1e-6)
