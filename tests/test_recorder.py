"""Flight recorder: ring semantics, incident dumps, trigger wiring.

The acceptance chain the ISSUE pins: an injected watchdog trip and an
injected worker/executor exception each produce a well-formed,
schema-checked incident file that ``mesh-tpu incidents`` reads in a
subprocess without initializing a jax backend.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mesh_tpu import obs
from mesh_tpu.obs.recorder import (
    SCHEMA_VERSION,
    FlightRecorder,
    get_recorder,
    list_incidents,
    recorder_enabled,
)
from mesh_tpu.serve import HealthMonitor, QueryService, Rung, ServeResult

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every key an incident file must carry (doc/observability.md schema).
#: schema v2 added "ledger": the latency ledger's newest request records.
#: schema v3 added "knob_history": the tuner's newest knob-change events.
#: schema v4 added "requests": the tail-sampling ring's retained traces.
_INCIDENT_KEYS = {
    "schema_version", "kind", "reason", "written_utc", "mono_at_dump",
    "context", "ring", "metrics", "health", "engine", "env", "ledger",
    "knob_history", "requests",
}


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    monkeypatch.delenv("MESH_TPU_OBS", raising=False)
    monkeypatch.delenv("MESH_TPU_RECORDER", raising=False)
    monkeypatch.setenv("MESH_TPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    obs.reset()
    yield
    obs.reset()


def _answer(rung_name):
    return ServeResult(np.zeros((1, 4), np.uint32),
                       np.zeros((4, 3), np.float64), rung_name)


def _ok_rung(name="ok"):
    return Rung(name, lambda mesh, points, chunk, timeout: _answer(name))


def _failing_rung(name="boom"):
    def fn(mesh, points, chunk, timeout):
        raise RuntimeError("%s rung failed" % name)
    return Rung(name, fn)


def _service(recorder, **kw):
    kw.setdefault("health",
                  HealthMonitor(watchdog=False, recorder=recorder))
    kw.setdefault("workers", 1)
    kw.setdefault("ladder", [_ok_rung()])
    return QueryService(recorder=recorder, **kw)


_PTS = np.zeros((4, 3), np.float32)


def _check_incident(path, reason):
    assert path is not None and os.path.exists(path)
    with open(path) as fh:
        incident = json.load(fh)
    assert set(incident) == _INCIDENT_KEYS
    assert incident["kind"] == "incident"
    assert incident["schema_version"] == SCHEMA_VERSION
    assert incident["reason"] == reason
    assert isinstance(incident["ring"], list)
    assert isinstance(incident["metrics"], dict)
    assert isinstance(incident["ledger"], list)
    assert all(k.startswith(("MESH_TPU_", "JAX_", "XLA_"))
               for k in incident["env"])
    return incident


# ---------------------------------------------------------------------------
# ring semantics


def test_ring_is_bounded_and_ordered():
    rec = FlightRecorder(capacity=16)
    for i in range(40):
        rec.record("tick", i=i)
    events = rec.events()
    assert len(events) == 16
    assert [e["i"] for e in events] == list(range(24, 40))
    assert all(e["kind"] == "tick" and "t" in e for e in events)


def test_env_kill_switch(monkeypatch):
    rec = FlightRecorder(capacity=8)
    monkeypatch.setenv("MESH_TPU_RECORDER", "0")
    assert not recorder_enabled()
    rec.record("dropped")
    assert rec.trigger("manual") is None
    assert rec.events() == []
    monkeypatch.delenv("MESH_TPU_RECORDER")
    assert recorder_enabled()
    rec.record("kept")
    assert [e["kind"] for e in rec.events()] == ["kept"]


def test_spans_land_in_global_ring(monkeypatch):
    monkeypatch.setenv("MESH_TPU_OBS", "1")
    with obs.span("recorded.region", q=7):
        pass
    spans = [e for e in get_recorder().events() if e["kind"] == "span"]
    assert spans and spans[-1]["name"] == "recorded.region"
    assert spans[-1]["attrs"]["q"] == 7
    assert spans[-1]["elapsed_s"] is not None


def test_sample_records_metric_deltas():
    rec = FlightRecorder(capacity=32)
    requests = obs.counter("mesh_tpu_serve_requests_total")
    obs.gauge("mesh_tpu_serve_queue_depth").set(3, tenant="a")
    requests.inc(5, tenant="a", outcome="ok")
    rec.sample()
    requests.inc(2, tenant="a", outcome="ok")
    rec.sample()
    samples = [e for e in rec.events() if e["kind"] == "metrics.sample"]
    assert len(samples) == 2
    assert samples[0]["deltas"]["mesh_tpu_serve_requests_total"] == 5
    assert samples[1]["deltas"]["mesh_tpu_serve_requests_total"] == 2
    assert samples[0]["queue_depths"] == {"a": 3}


# ---------------------------------------------------------------------------
# incident dumps


def test_trigger_writes_schema_complete_dump():
    rec = FlightRecorder(capacity=8)
    rec.record("serve.admit", tenant="a")
    obs.counter("mesh_tpu_serve_shed_total").inc(reason="queue_full")
    mon = HealthMonitor(watchdog=False, recorder=rec)
    path = rec.trigger("manual_test", context={"note": "hello"}, health=mon)
    incident = _check_incident(path, "manual_test")
    assert incident["context"] == {"note": "hello"}
    assert incident["ring"][0]["kind"] == "serve.admit"
    shed = incident["metrics"]["mesh_tpu_serve_shed_total"]["series"]
    assert shed[0]["labels"] == {"reason": "queue_full"}
    assert incident["health"]["state"] == "healthy"
    assert "trips" in incident["health"]
    # the dump itself is counted (next incident's metrics carry it)
    assert obs.REGISTRY.get("mesh_tpu_incident_dumps_total").value(
        reason="manual_test") == 1


def test_incident_carries_bounded_ledger_tail(monkeypatch):
    # schema v2: the newest MESH_TPU_LEDGER_TAIL request records ride
    # along so `mesh-tpu prof top <incident>` can attribute stage time
    monkeypatch.setenv("MESH_TPU_LEDGER_TAIL", "2")
    ledger = obs.get_ledger()
    for i in range(5):
        record = ledger.open(tenant="t%d" % i)
        record.stamp("queue")
        ledger.close(record, outcome="ok")
    rec = FlightRecorder(capacity=8)
    path = rec.trigger("ledger_tail_test")
    incident = _check_incident(path, "ledger_tail_test")
    assert len(incident["ledger"]) == 2
    assert [row["tenant"] for row in incident["ledger"]] == ["t3", "t4"]
    assert all("stages" in row and "outcome" in row
               for row in incident["ledger"])


def test_incident_carries_bounded_knob_tail(monkeypatch):
    # schema v3: the newest MESH_TPU_KNOB_TAIL knob-change events ride
    # along so `mesh-tpu tune history <incident>` can replay what the
    # tuner did leading up to the dump
    from mesh_tpu.utils import tuning

    monkeypatch.setenv("MESH_TPU_KNOB_TAIL", "2")
    monkeypatch.delenv("MESH_TPU_TUNER", raising=False)
    monkeypatch.delenv("MESH_TPU_COALESCE_WINDOW_MS", raising=False)
    for step in range(5):
        tuning.actuate("coalesce_window_ms", float(step + 1),
                       reason="test_step_%d" % step)
    rec = FlightRecorder(capacity=8)
    path = rec.trigger("knob_tail_test")
    incident = _check_incident(path, "knob_tail_test")
    assert len(incident["knob_history"]) == 2
    assert [e["after"] for e in incident["knob_history"]] == [4.0, 5.0]
    assert all(e["knob"] == "coalesce_window_ms"
               and e["action"] == "set"
               and "generation" in e and "reason" in e
               for e in incident["knob_history"])


def test_trigger_rate_limited_and_force_bypasses():
    rec = FlightRecorder(capacity=8, min_dump_interval_s=3600.0)
    first = rec.trigger("storm")
    assert first is not None
    assert rec.trigger("storm") is None          # held back
    forced = rec.trigger("storm", force=True)    # explicit API bypass
    assert forced is not None and forced != first


def test_incident_dir_keeps_newest_n(monkeypatch):
    monkeypatch.setenv("MESH_TPU_INCIDENT_KEEP", "3")
    rec = FlightRecorder(capacity=8)
    paths = [rec.trigger("prune_%d" % i, force=True) for i in range(5)]
    assert all(paths)
    kept = list_incidents()
    assert len(kept) == 3
    assert kept == sorted(paths[-3:])


# ---------------------------------------------------------------------------
# trigger sources (the ISSUE's trigger matrix)


def test_watchdog_trip_dumps_incident():
    rec = FlightRecorder(capacity=32)
    mon = HealthMonitor(watchdog=False, recorder=rec)
    mon.trip("dispatch_wedged")
    (path,) = list_incidents()
    incident = _check_incident(path, "watchdog_trip")
    assert incident["context"] == {"reason": "dispatch_wedged"}
    assert incident["health"]["state"] == "degraded"
    assert incident["health"]["trips"] == 1
    trips = [e for e in incident["ring"] if e["kind"] == "health.trip"]
    assert trips and trips[0]["reason"] == "dispatch_wedged"
    # acceptance: the injected-trip dump is readable by `mesh-tpu
    # incidents` in a subprocess (no jax backend init)
    proc = _run_cli(os.path.basename(path), "--dir", os.path.dirname(path),
                    "--json")
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["reason"] == "watchdog_trip"


def test_serve_worker_exception_dumps_incident(monkeypatch):
    rec = FlightRecorder(capacity=32)
    svc = _service(rec)
    try:
        monkeypatch.setattr(
            QueryService, "_execute",
            lambda self, req: (_ for _ in ()).throw(
                RuntimeError("injected worker fault")))
        svc.submit(object(), _PTS, tenant="a")
        deadline = time.time() + 10
        while not list_incidents() and time.time() < deadline:
            time.sleep(0.05)
    finally:
        svc.stop(write_stats=False)
    paths = [p for p in list_incidents()
             if "serve_worker_exception" in os.path.basename(p)]
    assert paths
    incident = _check_incident(paths[0], "serve_worker_exception")
    assert incident["context"]["error"] == "RuntimeError"
    assert "injected worker fault" in incident["context"]["detail"]
    assert incident["health"] is not None


def test_serve_error_and_reject_events_recorded():
    # the GLOBAL recorder: run_with_ladder's serve.retry goes through
    # get_recorder(), so this doubles as the end-to-end wiring check
    rec = get_recorder()
    svc = _service(rec, ladder=[_failing_rung()], max_queue_per_tenant=1,
                   default_deadline_s=0.2)
    try:
        fut = svc.submit(object(), _PTS, tenant="a")
        with pytest.raises(Exception):
            fut.result(timeout=30)
        svc.hold()
        try:
            svc.submit(object(), _PTS, tenant="a")
            with pytest.raises(Exception):
                svc.submit(object(), _PTS, tenant="a")  # queue_full
        finally:
            svc.release()
        svc.drain(timeout=10)
    finally:
        svc.stop(write_stats=False)
    kinds = [e["kind"] for e in rec.events()]
    assert "serve.admit" in kinds
    assert "serve.retry" in kinds        # ladder rung failure fell through
    assert "serve.error" in kinds        # request ultimately failed
    rejects = [e for e in rec.events() if e["kind"] == "serve.reject"]
    assert any(e["reason"] == "queue_full" for e in rejects)


def test_executor_exception_dumps_incident(monkeypatch):
    import types

    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    from mesh_tpu.engine import executor as executor_mod
    from mesh_tpu.errors import EngineShutdown

    mesh = types.SimpleNamespace(
        v=np.zeros((4, 3), np.float64),
        f=np.asarray([[0, 1, 2], [0, 1, 3], [0, 2, 3], [1, 2, 3]],
                     np.uint32))
    ex = executor_mod.EngineExecutor()
    monkeypatch.setattr(
        executor_mod.EngineExecutor, "_process",
        lambda self, batch: (_ for _ in ()).throw(
            SystemError("injected executor fault")))
    ex.submit("closest_point", mesh, _PTS)
    deadline = time.time() + 10
    while not list_incidents() and time.time() < deadline:
        time.sleep(0.05)
    paths = [p for p in list_incidents()
             if "executor_exception" in os.path.basename(p)]
    assert paths
    incident = _check_incident(paths[0], "executor_exception")
    assert incident["context"]["error"] == "SystemError"
    # the worker is dead: late submits fail fast instead of hanging
    with pytest.raises(EngineShutdown):
        deadline = time.time() + 10
        while time.time() < deadline:
            ex.submit("closest_point", mesh, _PTS)
            time.sleep(0.05)


# ---------------------------------------------------------------------------
# mesh-tpu incidents CLI (subprocess, no jax backend init)


def _run_cli(*argv, **env_overrides):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_overrides)
    return subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "incidents"] + list(argv),
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO)


def test_incidents_cli_empty_dir_exits_zero(tmp_path):
    proc = _run_cli("--dir", str(tmp_path / "none"))
    assert proc.returncode == 0
    assert "no incidents" in proc.stdout


def test_incidents_cli_lists_and_shows(tmp_path):
    rec = FlightRecorder(capacity=8)
    rec.record("serve.reject", tenant="a", reason="queue_full")
    mon = HealthMonitor(watchdog=False, recorder=rec)
    path = rec.trigger("cli_test", context={"k": "v"}, health=mon)
    directory = os.path.dirname(path)

    listing = _run_cli("--dir", directory)
    assert listing.returncode == 0
    assert os.path.basename(path) in listing.stdout
    assert "reason=cli_test" in listing.stdout

    shown = _run_cli(os.path.basename(path), "--dir", directory)
    assert shown.returncode == 0
    assert "reason: cli_test" in shown.stdout
    assert "serve.reject" in shown.stdout

    raw = _run_cli(os.path.basename(path), "--dir", directory, "--json")
    incident = json.loads(raw.stdout)
    assert incident["reason"] == "cli_test"
    assert incident["context"] == {"k": "v"}


def test_incidents_cli_corrupt_file_exits_one(tmp_path):
    bad = tmp_path / "incident-000-bad-001.json"
    bad.write_text("{not json")
    proc = _run_cli(bad.name, "--dir", str(tmp_path))
    assert proc.returncode == 1
    assert "unreadable" in proc.stderr
