"""Geometry kernel tests (ports the reference's oracle/property style,
tests/test_geometry.py: rodrigues vs cv2, CrossProduct vs np.cross,
VertNormals consistency, barycentric reconstruction, finite-difference
stability)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mesh_tpu.geometry import (
    barycentric_coordinates_of_projection,
    cross,
    rodrigues,
    rodrigues2rotmat,
    rotmat2rodrigues,
    tri_normals,
    tri_normals_scaled,
    triangle_area,
    vert_normals,
)
from .fixtures import box, icosphere

cv2 = pytest.importorskip("cv2", reason="cv2 oracle for rodrigues")


class TestCross:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = rng.randn(100, 3).astype(np.float32)
        b = rng.randn(100, 3).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(cross(jnp.asarray(a), jnp.asarray(b))),
            np.cross(a, b),
            atol=1e-5,
        )


class TestTriNormals:
    def test_box_face_normals(self):
        v, f = box()
        n = np.asarray(tri_normals(jnp.asarray(v, jnp.float32), jnp.asarray(f, jnp.int32)))
        expected = np.array(
            [[0, 0, -1], [0, 0, -1], [0, 0, 1], [0, 0, 1],
             [0, -1, 0], [0, -1, 0], [0, 1, 0], [0, 1, 0],
             [-1, 0, 0], [-1, 0, 0], [1, 0, 0], [1, 0, 0]],
            dtype=np.float32,
        )
        np.testing.assert_allclose(n, expected, atol=1e-6)

    def test_area(self):
        v, f = box(size=2.0)
        areas = np.asarray(triangle_area(jnp.asarray(v, jnp.float32), jnp.asarray(f, jnp.int32)))
        np.testing.assert_allclose(areas, np.full(12, 2.0), atol=1e-5)

    def test_finite_difference_stability(self):
        """Scaled normals are differentiable; grad matches finite differences
        (analog of reference tests/test_geometry.py:110-145)."""
        rng = np.random.RandomState(1)
        v = rng.randn(10, 3).astype(np.float32)
        f = jnp.asarray(rng.randint(0, 10, (6, 3)), jnp.int32)

        def loss(vv):
            return jnp.sum(tri_normals_scaled(vv, f) ** 2)

        g = np.asarray(jax.grad(loss)(jnp.asarray(v)))
        eps = 1e-3
        for idx in [(0, 0), (3, 1), (9, 2)]:
            vp = v.copy(); vp[idx] += eps
            vm = v.copy(); vm[idx] -= eps
            fd = (loss(jnp.asarray(vp)) - loss(jnp.asarray(vm))) / (2 * eps)
            assert abs(g[idx] - float(fd)) < 1e-1 * max(1.0, abs(float(fd)))


class TestVertNormals:
    def test_sphere_normals_radial(self):
        """Reference tests/test_mesh.py:111-118: sphere vertex normals are
        approximately radial, MSE < 0.05."""
        v, f = icosphere(2)
        n = np.asarray(vert_normals(jnp.asarray(v, jnp.float32), jnp.asarray(f, jnp.int32)))
        radial = v / np.linalg.norm(v, axis=1, keepdims=True)
        mse = np.mean(np.sum((n - radial) ** 2, axis=1))
        assert mse < 0.05

    def test_batched_matches_loop(self):
        """The headline capability: leading batch axis over shared topology."""
        rng = np.random.RandomState(2)
        v, f = icosphere(1)
        batch = jnp.asarray(
            v[None] + 0.01 * rng.randn(4, *v.shape), jnp.float32
        )
        fj = jnp.asarray(f, jnp.int32)
        batched = np.asarray(vert_normals(batch, fj))
        for i in range(4):
            single = np.asarray(vert_normals(batch[i], fj))
            np.testing.assert_allclose(batched[i], single, atol=1e-6)

    def test_matches_mesh_method(self):
        """Two formulations agree (reference tests/test_geometry.py:59-68)."""
        from mesh_tpu import Mesh

        v, f = icosphere(1)
        m = Mesh(v=v, f=f)
        np.testing.assert_allclose(
            m.estimate_vertex_normals(),
            np.asarray(vert_normals(jnp.asarray(v, jnp.float32), jnp.asarray(f, jnp.int32))),
            atol=1e-6,
        )


class TestBarycentric:
    def test_reconstruction(self):
        """b0*q + b1*(q+u) + b2*(q+v) reconstructs the in-plane projection."""
        rng = np.random.RandomState(3)
        q = rng.randn(50, 3)
        u = rng.randn(50, 3)
        v = rng.randn(50, 3)
        p = q + rng.rand(50, 1) * u + rng.rand(50, 1) * v  # in-plane points
        b = np.asarray(barycentric_coordinates_of_projection(p, q, u, v))
        recon = b[:, 0:1] * q + b[:, 1:2] * (q + u) + b[:, 2:3] * (q + v)
        np.testing.assert_allclose(recon, p, atol=1e-4)
        np.testing.assert_allclose(b.sum(axis=1), np.ones(50), atol=1e-5)

    def test_degenerate_triangle_no_nan(self):
        u = np.array([[1.0, 0, 0]])
        b = np.asarray(
            barycentric_coordinates_of_projection(
                np.array([[0.5, 0.2, 0.0]]), np.zeros((1, 3)), u, 2 * u
            )
        )
        assert np.all(np.isfinite(b))


class TestRodrigues:
    def test_forward_vs_cv2(self):
        rng = np.random.RandomState(4)
        for r in [np.zeros(3), np.array([np.pi, 0, 0]), *rng.randn(10, 3)]:
            R, J = rodrigues(r)
            Rc, Jc = cv2.Rodrigues(r)
            # XLA CPU lowers sin() to a vectorized approximation with ~4e-9
            # absolute error even in f64; well inside the 1e-5 parity bar.
            np.testing.assert_allclose(R, Rc, atol=1e-7)
            np.testing.assert_allclose(J, Jc, atol=1e-6)

    def test_inverse_vs_cv2(self):
        rng = np.random.RandomState(5)
        for r in rng.randn(10, 3):
            Rc = cv2.Rodrigues(r)[0]
            out, Jinv = rodrigues(Rc)
            oc, Jic = cv2.Rodrigues(Rc)
            np.testing.assert_allclose(out, oc, atol=1e-7)
            np.testing.assert_allclose(Jinv, Jic, atol=1e-6)

    def test_batched_device_roundtrip(self):
        rng = np.random.RandomState(6)
        r = jnp.asarray(rng.randn(32, 3) * 0.9, jnp.float32)
        R = np.asarray(rodrigues2rotmat(r), dtype=np.float64)
        # orthonormality (checked with numpy matmul: XLA f32 matmul runs at
        # reduced precision by default on TPU-profile builds)
        np.testing.assert_allclose(
            R @ np.swapaxes(R, -1, -2),
            np.broadcast_to(np.eye(3), R.shape),
            atol=1e-5,
        )
        back = np.asarray(rotmat2rodrigues(R))
        np.testing.assert_allclose(back, np.asarray(r), atol=1e-4)

    def test_differentiable_at_zero(self):
        g = jax.jacfwd(rodrigues2rotmat)(jnp.zeros(3))
        assert np.all(np.isfinite(np.asarray(g)))
