"""Reference-named geometry API (geometry/compat.py).

These are the chumpy-era symbols downstream body-model pipelines import
directly; shapes must match the reference exactly (flattened 1-D between
steps, reference tri_normals.py:19-72 / vert_normals.py:14-34 /
cross_product.py:10-32).
"""

import numpy as np
import scipy.sparse as sp

from mesh_tpu.geometry import (
    CrossProduct,
    MatVecMult,
    NormalizedNx3,
    NormalizeRows,
    TriEdges,
    TriNormals,
    TriNormalsScaled,
    TriToScaledNormal,
    VertNormals,
    VertNormalsScaled,
)
from tests.fixtures import icosphere


def _numpy_reference(v, f):
    """Straight numpy re-derivation of the reference formulas."""
    e10 = v[f[:, 1]] - v[f[:, 0]]
    e20 = v[f[:, 2]] - v[f[:, 0]]
    fn_scaled = np.cross(e10, e20)
    norms = np.sqrt((fn_scaled ** 2).sum(1))
    norms[norms == 0] = 1
    fn = fn_scaled / norms[:, None]
    vn = np.zeros_like(v)
    for k in range(3):
        np.add.at(vn, f[:, k], fn_scaled)
    vnorms = np.sqrt((vn ** 2).sum(1))
    vnorms[vnorms == 0] = 1
    return fn_scaled, fn, vn / vnorms[:, None]


class TestCompatShapes:
    def test_flattened_shapes(self):
        v, f = icosphere(1)
        F, V = len(f), len(v)
        assert TriNormals(v, f).shape == (F * 3,)
        assert TriNormalsScaled(v, f).shape == (F * 3,)
        assert TriEdges(v, f, 1, 0).shape == (F * 3,)
        assert VertNormals(v, f).shape == (V * 3,)
        assert TriToScaledNormal(v, f).shape == (F, 3)  # the one 2-D output
        assert NormalizeRows(np.ones((4, 3))).shape == (4, 3)
        assert NormalizedNx3(np.ones(12)).shape == (12,)

    def test_accepts_flattened_input(self):
        v, f = icosphere(1)
        np.testing.assert_allclose(
            TriNormals(v.flatten(), f), TriNormals(v, f), atol=0
        )


class TestCompatValues:
    def test_tri_normals_match_numpy(self):
        v, f = icosphere(2)
        fn_scaled, fn, _ = _numpy_reference(v, f)
        np.testing.assert_allclose(
            TriNormalsScaled(v, f), fn_scaled.flatten(), atol=1e-6
        )
        np.testing.assert_allclose(TriNormals(v, f), fn.flatten(), atol=1e-6)
        np.testing.assert_allclose(
            TriToScaledNormal(v, f), fn_scaled, atol=1e-6
        )

    def test_vert_normals_match_numpy(self):
        v, f = icosphere(2)
        _, _, vn = _numpy_reference(v, f)
        np.testing.assert_allclose(VertNormals(v, f), vn.flatten(), atol=1e-6)
        np.testing.assert_allclose(
            VertNormalsScaled(v, f), VertNormals(v, f), atol=0
        )  # reference quirk: "scaled" variant normalizes too

    def test_cross_product_matches_numpy(self):
        rng = np.random.RandomState(0)
        a, b = rng.randn(2, 30)
        np.testing.assert_allclose(
            CrossProduct(a, b),
            np.cross(a.reshape(-1, 3), b.reshape(-1, 3)).flatten(),
            atol=1e-12,
        )

    def test_normalized_nx3_zero_guard(self):
        x = np.array([0.0, 0, 0, 3, 0, 0])
        np.testing.assert_allclose(NormalizedNx3(x), [0, 0, 0, 1, 0, 0], atol=0)

    def test_mat_vec_mult(self):
        mtx = sp.csc_matrix(np.arange(12).reshape(3, 4))
        vec = np.arange(4)
        np.testing.assert_allclose(
            MatVecMult(mtx, vec), mtx.toarray() @ vec, atol=0
        )
