"""The examples must keep running end-to-end (subprocess, small sizes)."""

import os
import subprocess
import sys


def test_register_scan_example(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "register_scan.py"),
            "--steps", "20", "--out", str(tmp_path),
        ],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PYTHONPATH": repo},
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "surface error" in res.stdout
    assert (tmp_path / "fitted.ply").exists()
    assert (tmp_path / "scan.ply").exists()


def test_fit_multichip_example(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "fit_multichip.py"),
            "--steps", "8", "--ckpt", str(tmp_path / "ckpt"),
        ],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": repo},
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "checkpoint resume bit-identical: ok" in res.stdout


def test_measure_body_example(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "body")
    res = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "measure_body.py"),
            "--batch", "2", "--out", out,
        ],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PYTHONPATH": repo},
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "chest" in res.stdout and "waist" in res.stdout
    assert (tmp_path / "body.obj").exists()
    assert (tmp_path / "body_curves.obj").exists()


def test_hand_body_contact_example(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "hand_body_contact.py"),
            "--out", str(tmp_path),
        ],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PYTHONPATH": repo},
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "intersecting hand faces" in res.stdout
    assert "contact vertices" in res.stdout
    assert (tmp_path / "hand.ply").exists()
    assert (tmp_path / "body.ply").exists()
