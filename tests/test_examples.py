"""The examples must keep running end-to-end (subprocess, small sizes)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name, *args, timeout=300):
    """Run examples/<name> in a subprocess with the repo PREPENDED to
    PYTHONPATH — clobbering it would drop /root/.axon_site (the axon PJRT
    plugin) and break backend init on the TPU host."""
    pythonpath = os.pathsep.join(
        p for p in (_REPO, os.environ.get("PYTHONPATH", "")) if p
    )
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", name), *args],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "PYTHONPATH": pythonpath},
    )
    assert res.returncode == 0, res.stderr[-2000:]
    return res


def test_register_scan_example(tmp_path):
    res = _run_example(
        "register_scan.py", "--steps", "20", "--out", str(tmp_path)
    )
    assert "surface error" in res.stdout
    assert (tmp_path / "fitted.ply").exists()
    assert (tmp_path / "scan.ply").exists()


def test_batch_pipeline_example():
    res = _run_example("batch_pipeline.py", "--batch", "3", "--queries", "64")
    assert "results identical" in res.stdout
    assert "amortization" in res.stdout


# 8-device CPU simulation end-to-end: minutes-scale, like the sharded
# tests it drives; tier-1 (-m 'not slow') skips it
@pytest.mark.slow
def test_fit_multichip_example(tmp_path):
    res = _run_example(
        "fit_multichip.py", "--steps", "8", "--ckpt", str(tmp_path / "ckpt"),
        timeout=600,
    )
    assert "checkpoint resume bit-identical: ok" in res.stdout


def test_measure_body_example(tmp_path):
    res = _run_example(
        "measure_body.py", "--batch", "2", "--out", str(tmp_path / "body")
    )
    assert "chest" in res.stdout and "waist" in res.stdout
    assert (tmp_path / "body.obj").exists()
    assert (tmp_path / "body_curves.obj").exists()


def test_hand_body_contact_example(tmp_path):
    res = _run_example("hand_body_contact.py", "--out", str(tmp_path))
    assert "intersecting hand faces" in res.stdout
    assert "contact vertices" in res.stdout
    assert (tmp_path / "hand.ply").exists()
    assert (tmp_path / "body.ply").exists()


@pytest.mark.skipif(
    __import__("jax").__version_info__ < (0, 5, 0),
    reason="multi-process CPU collectives need jax >= 0.5",
)
def test_multihost_scan_example():
    res = _run_example("multihost_scan.py")
    out = res.stdout
    for pid in (0, 1):
        assert "[host %d] 10000 global queries answered" % pid in out, (
            out[-2000:] + res.stderr[-500:]
        )
