"""Timing discipline lint (ISSUE PR-2 satellite e).

Thin wrapper over the meshlint OBS004 rule (``mesh_tpu.analysis``):
raw clock reads scattered through the hot path bypass the span tracer's
sync-aware measurement and the overhead gate, so every wall-clock read
in ``mesh_tpu/`` must go through ``utils/profiling.py`` (Timer /
time_fn) or ``obs/`` (obs.clock re-exports the clocks; spans build on
them).  ``viewer/`` is exempt (UI latencies are not hot-path
measurements), and so is ``analysis/`` itself (offline lint tooling —
its own elapsed-time stamp is not a measurement of anything on-device).
The exemption list lives with the rule; this test runs it over the
real tree so `pytest` and `mesh-tpu lint` can never disagree.
"""

import os

from mesh_tpu.analysis import build_project
from mesh_tpu.analysis.rules.obs import ObservabilityHygieneRule

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_no_raw_clock_reads_outside_profiling_and_obs():
    project, failures = build_project(_REPO)
    assert not failures, [f.render() for f in failures]
    rule = ObservabilityHygieneRule()
    offenders = []
    for ctx in project.contexts:
        for finding in rule.check(ctx):
            if finding.rule == "OBS004":
                offenders.append("%s:%d: %s" % (
                    finding.path, finding.line, ctx.line(finding.line)))
    assert not offenders, (
        "raw clock reads outside utils/profiling.py and obs/ "
        "(route them through obs.clock or Timer):\n"
        + "\n".join(offenders)
    )
