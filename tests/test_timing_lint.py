"""Timing discipline lint (ISSUE PR-2 satellite e).

Raw clock reads scattered through the hot path are how timing code rots:
they bypass the span tracer's sync-aware measurement and the overhead
gate.  Every wall-clock read in ``mesh_tpu/`` must go through
``utils/profiling.py`` (Timer / time_fn) or ``obs/`` (obs.clock
re-exports the clocks; spans build on them).  ``viewer/`` is exempt —
its deadlines and UI latencies are not hot-path measurements.
"""

import os
import re

_PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "mesh_tpu"
)

#: a raw clock CALL — `monotonic = time.perf_counter` aliasing (obs.clock)
#: deliberately does not match
_RAW_CLOCK = re.compile(
    r"\btime\.(time|perf_counter|monotonic|process_time)\s*\("
)

_EXEMPT = (
    os.path.join("utils", "profiling.py"),
    "obs" + os.sep,
    "viewer" + os.sep,
)


def test_no_raw_clock_reads_outside_profiling_and_obs():
    offenders = []
    for root, _dirs, files in os.walk(_PKG):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, _PKG)
            if any(rel.startswith(e) or rel == e.rstrip(os.sep)
                   for e in _EXEMPT):
                continue
            with open(path, encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, 1):
                    if _RAW_CLOCK.search(line):
                        offenders.append("%s:%d: %s"
                                         % (rel, lineno, line.strip()))
    assert not offenders, (
        "raw clock reads outside utils/profiling.py and obs/ "
        "(route them through obs.clock or Timer):\n"
        + "\n".join(offenders)
    )
