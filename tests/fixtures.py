"""Programmatically generated tiny fixtures (the reference checks in
data/unittest/*.obj|ply; we generate equivalent analytic geometry so the test
suite is self-contained — reference goldens are used only in guarded parity
tests)."""

import numpy as np


def box(size=1.0, center=(0.0, 0.0, 0.0)):
    """Unit box: 8 verts, 12 faces, outward-facing normals."""
    c = np.asarray(center, dtype=np.float64)
    h = size / 2.0
    v = np.array([
        [-h, -h, -h], [h, -h, -h], [h, h, -h], [-h, h, -h],
        [-h, -h, h], [h, -h, h], [h, h, h], [-h, h, h],
    ]) + c
    f = np.array([
        [0, 2, 1], [0, 3, 2],      # z = -h (normal -z)
        [4, 5, 6], [4, 6, 7],      # z = +h (normal +z)
        [0, 1, 5], [0, 5, 4],      # y = -h (normal -y)
        [2, 3, 7], [2, 7, 6],      # y = +h (normal +y)
        [0, 4, 7], [0, 7, 3],      # x = -h (normal -x)
        [1, 2, 6], [1, 6, 5],      # x = +h (normal +x)
    ], dtype=np.uint32)
    return v, f


def icosphere(subdivisions=2, radius=1.0):
    from mesh_tpu.sphere import _icosphere

    v, f = _icosphere(subdivisions)
    return v * radius, f.astype(np.uint32)


def cylinder(n=16, radius=1.0, height=2.0):
    """Open-ended triangulated cylinder around the z axis."""
    theta = np.linspace(0, 2 * np.pi, n, endpoint=False)
    ring = np.stack([radius * np.cos(theta), radius * np.sin(theta)], axis=1)
    bottom = np.concatenate([ring, np.full((n, 1), -height / 2)], axis=1)
    top = np.concatenate([ring, np.full((n, 1), height / 2)], axis=1)
    v = np.vstack([bottom, top])
    f = []
    for i in range(n):
        j = (i + 1) % n
        f.append([i, j, n + i])
        f.append([j, n + j, n + i])
    return v, np.array(f, dtype=np.uint32)


def separated_sphere_queries(n, seed):
    """Query points clearly inside or outside a unit sphere (r in
    [0.3, 0.7] or [1.3, 2.0]), away from the surface: the nearest face is
    then generically unique, so argmin agreement between kernel variants
    is a meaningful assertion (gaussian points near the surface are
    near-equidistant to many faces and tie-flip legitimately)."""
    rng = np.random.RandomState(seed)
    u = rng.randn(n, 3)
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    r = np.where(rng.rand(n) < 0.5,
                 rng.uniform(1.3, 2.0, n), rng.uniform(0.3, 0.7, n))
    return (u * r[:, None]).astype(np.float32)
