"""Body model (LBS) tests: rest-pose identity, rigid-transform equivariance,
batching, differentiability."""

import numpy as np
import jax
import jax.numpy as jnp

from mesh_tpu.models import lbs, smpl_sized_sphere, synthetic_body_model


def _small_model():
    from mesh_tpu.sphere import _icosphere

    v, f = _icosphere(1)
    return synthetic_body_model(seed=1, n_betas=4, n_joints=6,
                                template=(v, f.astype(np.int32)))


class TestSmplSizedSphere:
    def test_exact_smpl_shapes(self):
        v, f = smpl_sized_sphere()
        assert v.shape == (6890, 3)
        assert f.shape == (13776, 3)
        # closed manifold: every edge shared by exactly 2 faces
        edges = np.sort(
            np.concatenate([f[:, [0, 1]], f[:, [1, 2]], f[:, [2, 0]]]), axis=1
        )
        _, counts = np.unique(edges, axis=0, return_counts=True)
        assert (counts == 2).all()


class TestLBS:
    def test_rest_pose_is_template(self):
        model = _small_model()
        verts, joints = lbs(
            model,
            jnp.zeros((model.num_betas,)),
            jnp.zeros((model.num_joints, 3)),
        )
        np.testing.assert_allclose(
            np.asarray(verts), np.asarray(model.v_template), atol=1e-5
        )

    def test_global_rotation_is_rigid(self):
        """Rotating only the root joint rigidly rotates the whole body about
        the root."""
        model = _small_model()
        pose = np.zeros((model.num_joints, 3), np.float32)
        pose[0] = [0.0, 0.0, np.pi / 2]
        verts, joints = lbs(model, jnp.zeros(model.num_betas), jnp.asarray(pose))
        rest, rest_joints = lbs(
            model, jnp.zeros(model.num_betas), jnp.zeros((model.num_joints, 3))
        )
        Rz = np.array([[0, -1, 0], [1, 0, 0], [0, 0, 1.0]])
        root = np.asarray(rest_joints)[0]
        expected = (np.asarray(rest) - root) @ Rz.T + root
        np.testing.assert_allclose(np.asarray(verts), expected, atol=1e-4)

    def test_translation(self):
        model = _small_model()
        t = jnp.asarray([1.0, 2.0, 3.0])
        verts, joints = lbs(
            model, jnp.zeros(model.num_betas),
            jnp.zeros((model.num_joints, 3)), t
        )
        rest, _ = lbs(
            model, jnp.zeros(model.num_betas), jnp.zeros((model.num_joints, 3))
        )
        np.testing.assert_allclose(
            np.asarray(verts), np.asarray(rest) + np.asarray(t), atol=1e-5
        )

    def test_batched_matches_single(self):
        model = _small_model()
        rng = np.random.RandomState(0)
        betas = jnp.asarray(rng.randn(3, model.num_betas) * 0.3, jnp.float32)
        pose = jnp.asarray(rng.randn(3, model.num_joints, 3) * 0.2, jnp.float32)
        batched, _ = lbs(model, betas, pose)
        for i in range(3):
            single, _ = lbs(model, betas[i], pose[i])
            np.testing.assert_allclose(
                np.asarray(batched[i]), np.asarray(single), atol=1e-5
            )

    def test_shape_blendshapes_move_vertices(self):
        model = _small_model()
        betas = jnp.zeros(model.num_betas).at[0].set(2.0)
        shaped, _ = lbs(model, betas, jnp.zeros((model.num_joints, 3)))
        rest, _ = lbs(model, jnp.zeros(model.num_betas), jnp.zeros((model.num_joints, 3)))
        assert float(jnp.abs(shaped - rest).max()) > 1e-3

    def test_differentiable(self):
        model = _small_model()

        def loss(pose):
            v, _ = lbs(model, jnp.zeros(model.num_betas), pose)
            return jnp.sum(v ** 2)

        g = jax.grad(loss)(jnp.zeros((model.num_joints, 3)))
        assert np.all(np.isfinite(np.asarray(g)))
        assert float(jnp.abs(g).max()) > 0  # gradient at rest pose is nonzero

    def test_jit_compiles(self):
        model = _small_model()
        fn = jax.jit(lambda b, p: lbs(model, b, p)[0])
        out = fn(jnp.zeros(model.num_betas), jnp.zeros((model.num_joints, 3)))
        assert out.shape == (model.num_vertices, 3)


class TestModelFamilies:
    def test_family_architectures(self):
        import jax
        import jax.numpy as jnp

        from mesh_tpu.models import MODEL_FAMILIES, lbs, synthetic_family_model

        for family, (n_v, n_j, n_b) in MODEL_FAMILIES.items():
            model = synthetic_family_model(family)
            assert model.num_vertices == n_v, family
            assert model.num_joints == n_j, family
            assert model.num_betas == n_b, family
            # one jitted forward at batch 2 runs and stays finite
            verts, joints = jax.jit(lambda b, p, m=model: lbs(m, b, p))(
                jnp.zeros((2, n_b)), jnp.zeros((2, n_j, 3))
            )
            assert verts.shape == (2, n_v, 3)
            assert joints.shape == (2, n_j, 3)
            assert bool(jnp.all(jnp.isfinite(verts)))

    def test_unknown_family_raises(self):
        import pytest

        from mesh_tpu.models import synthetic_family_model

        with pytest.raises(ValueError, match="unknown family"):
            synthetic_family_model("ghost")


def test_npz_roundtrip(tmp_path):
    """save_body_model_npz writes the interchange key set
    load_body_model_npz reads; a forward pass through the round-tripped
    model is bit-identical."""
    from mesh_tpu.models import (
        load_body_model_npz, save_body_model_npz, synthetic_family_model,
    )

    model = synthetic_family_model("mano", seed=3)
    path = str(tmp_path / "mano.npz")
    save_body_model_npz(model, path)
    back = load_body_model_npz(path)
    assert back.parents == model.parents
    betas = jnp.asarray(np.random.RandomState(0).randn(2, model.num_betas),
                        jnp.float32)
    pose = jnp.asarray(
        np.random.RandomState(1).randn(2, model.num_joints, 3) * 0.1,
        jnp.float32,
    )
    v0, j0 = lbs(model, betas, pose)
    v1, j1 = lbs(back, betas, pose)
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
    np.testing.assert_array_equal(np.asarray(j0), np.asarray(j1))
