"""Latency ledger, windowed series, and ``mesh-tpu prof`` attribution.

The acceptance chain the ISSUE pins: stage stamps stay monotone and sum
to the admit-to-respond total, the ring is bounded (env-resizable,
floor 16), concurrent writers never lose rows, windowed percentiles are
exact under a fake clock, and ``prof diff`` names the stage a fault-
injected slowdown landed in — end to end through the CLI rc matrix.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from mesh_tpu import obs
from mesh_tpu.obs import prof
from mesh_tpu.obs.ledger import (
    LEDGER_STAGES,
    LatencyLedger,
    bind_current,
    current_record,
    ledger_enabled,
)
from mesh_tpu.obs.metrics import Registry
from mesh_tpu.obs.series import SampleRing, WindowedSeries

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ("MESH_TPU_LEDGER", "MESH_TPU_LEDGER_CAPACITY",
                "MESH_TPU_LEDGER_TAIL", "MESH_TPU_OBS"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


class FakeClock(object):
    """Callable monotonic clock a test advances by hand."""

    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)
        return self.t


def _fake_ledger(capacity=64, t=100.0):
    clk = FakeClock(t)
    return LatencyLedger(capacity=capacity, registry=Registry(),
                         clock=clk), clk


def _serve_one(ledger, clk, tenant="t1", backend="xla",
               queue_s=0.001, dispatch_s=0.002, device_s=0.003):
    """One synthetic request: fault-inject per-stage cost via the fake
    clock, exactly where the real stamp sites sit."""
    rec = ledger.open(tenant=tenant)
    clk.advance(queue_s)
    rec.stamp("queue")
    clk.advance(dispatch_s)
    rec.stamp("dispatch")
    clk.advance(device_s)
    rec.stamp("device")
    clk.advance(0.0005)
    return ledger.close(rec, backend=backend)


# ---------------------------------------------------------------------------
# record semantics


class TestRecordStamps:

    def test_unknown_stage_raises(self):
        led, _ = _fake_ledger()
        rec = led.open()
        with pytest.raises(ValueError, match="unknown ledger stage"):
            rec.stamp("warmup")

    def test_stage_seconds_chain_and_sum(self):
        """Durations chain across missing stages and sum to the span
        from admit to the last stamp — no gap is ever double-counted."""
        led, clk = _fake_ledger()
        rec = led.open()
        clk.advance(0.010)
        rec.stamp("queue")
        clk.advance(0.030)          # coalesce + pad never stamped
        rec.stamp("dispatch")
        clk.advance(0.005)
        rec.stamp("device")
        stages = rec.stage_seconds()
        assert list(stages) == ["queue", "dispatch", "device"]
        assert stages["queue"] == pytest.approx(0.010)
        assert stages["dispatch"] == pytest.approx(0.030)
        assert stages["device"] == pytest.approx(0.005)
        assert sum(stages.values()) == pytest.approx(
            max(rec.stamps.values()) - rec.t_admit)

    def test_out_of_order_stamp_clamps_to_zero(self):
        led, clk = _fake_ledger()
        rec = led.open()
        clk.advance(0.010)
        rec.stamp("dispatch")
        rec.stamp("queue", t=rec.t_admit + 0.020)   # later than dispatch
        stages = rec.stage_seconds()
        assert stages["queue"] == pytest.approx(0.020)
        assert stages["dispatch"] == 0.0            # clamped, not negative

    def test_close_stamps_respond_and_rows_carry_provenance(self):
        led, clk = _fake_ledger()
        row = _serve_one(led, clk, tenant="acme", backend="pallas")
        assert row["tenant"] == "acme"
        assert row["backend"] == "pallas"
        assert row["outcome"] == "ok"
        assert "respond" in row["stages"]
        assert row["total_s"] == pytest.approx(sum(row["stages"].values()))
        order = [s for s in LEDGER_STAGES if s in row["stages"]]
        assert list(row["stages"]) == order

    def test_close_observes_stage_histogram_with_backend_label(self):
        reg = Registry()
        clk = FakeClock()
        led = LatencyLedger(capacity=16, registry=reg, clock=clk)
        _serve_one(led, clk, backend="pallas_stream")
        hist = reg.get("mesh_tpu_request_stage_seconds")
        stat = hist.stat(stage="dispatch", backend="pallas_stream")
        assert stat["count"] == 1
        assert stat["sum"] == pytest.approx(0.002)

    def test_bind_current_nests_and_restores(self):
        led, _ = _fake_ledger()
        outer, inner = led.open(), led.open()
        assert current_record() is None
        with bind_current(outer):
            assert current_record() is outer
            with bind_current(inner):
                assert current_record() is inner
            assert current_record() is outer
        assert current_record() is None


# ---------------------------------------------------------------------------
# ring bounds + kill switch


class TestRingBounds:

    def test_env_capacity_bounds_ring(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_LEDGER_CAPACITY", "17")
        obs.reset()                         # clear() re-reads the knob
        led = obs.get_ledger()
        for i in range(40):
            led.close(led.open(tenant="t%d" % i))
        assert len(led) == 17
        rows = led.records()
        assert rows[0]["tenant"] == "t23"   # oldest evicted
        assert rows[-1]["tenant"] == "t39"

    def test_capacity_floor_is_16(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_LEDGER_CAPACITY", "3")
        obs.reset()
        led = obs.get_ledger()
        for i in range(40):
            led.close(led.open())
        assert len(led) == 16

    def test_kill_switch_disables_record_creation(self, monkeypatch):
        assert ledger_enabled()
        monkeypatch.setenv("MESH_TPU_LEDGER", "0")
        assert not ledger_enabled()
        led = obs.get_ledger()
        assert led.open(tenant="t") is None
        assert led.close(None) is None      # stamp sites are None-guarded
        assert len(led) == 0

    def test_tail_defaults_to_env_knob(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_LEDGER_TAIL", "4")
        led, clk = _fake_ledger()
        for i in range(9):
            led.close(led.open(tenant="t%d" % i))
        tail = led.tail()
        assert [r["tenant"] for r in tail] == ["t5", "t6", "t7", "t8"]
        assert [r["tenant"] for r in led.tail(2)] == ["t7", "t8"]

    def test_concurrent_writers_lose_nothing(self):
        reg = Registry()
        led = LatencyLedger(capacity=4096, registry=reg)
        n_threads, per_thread = 8, 50

        def work(tid):
            for i in range(per_thread):
                rec = led.open(tenant="w%d" % tid)
                rec.stamp("queue")
                rec.stamp("dispatch")
                led.close(rec, backend="xla")

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rows = led.records()
        assert len(rows) == n_threads * per_thread
        assert all(isinstance(r["stages"], dict) for r in rows)
        hist = reg.get("mesh_tpu_request_stage_seconds")
        stat = hist.stat(stage="queue", backend="xla")
        assert stat["count"] == n_threads * per_thread


# ---------------------------------------------------------------------------
# windowed series under a fake clock


class TestWindowedSeries:

    def _filled(self):
        """Registry + series: 20 fast (2 ms) observations in the first
        window, 20 slow (90 ms) in the second, snapshotted at t=10 and
        t=70."""
        reg = Registry()
        ws = WindowedSeries(registry=reg, resolution_s=1.0, capacity=64,
                            clock=FakeClock(0.0))
        hist = reg.histogram("mesh_tpu_request_stage_seconds")
        req = reg.counter("mesh_tpu_serve_requests_total")
        for _ in range(20):
            hist.observe(0.002, stage="queue", backend="xla")
            req.inc(tenant="t1", outcome="ok")
        ws.tick(now=10.0)
        for _ in range(20):
            hist.observe(0.090, stage="queue", backend="xla")
            req.inc(tenant="t1", outcome="ok")
        ws.tick(now=70.0)
        return reg, ws

    def test_trailing_window_percentile_sees_only_the_delta(self):
        _, ws = self._filled()
        p50 = ws.percentile("mesh_tpu_request_stage_seconds", 0.50,
                            window_s=30.0, now=70.0)
        # only the 90 ms phase is inside [40, 70]: interpolated inside
        # the (0.05, 0.1] bucket
        assert 0.05 < p50 <= 0.1

    def test_full_history_percentile_mixes_both_phases(self):
        _, ws = self._filled()
        p50 = ws.percentile("mesh_tpu_request_stage_seconds", 0.50,
                            window_s=500.0, now=70.0)
        # rank 20 of 40 lands in the fast phase's (1e-3, 2.5e-3] bucket
        assert p50 < 0.005

    def test_rate_and_delta_difference_window_boundary(self):
        _, ws = self._filled()
        assert ws.delta("mesh_tpu_serve_requests_total",
                        window_s=30.0, now=70.0) == 20
        assert ws.rate("mesh_tpu_serve_requests_total",
                       window_s=30.0, now=70.0) == pytest.approx(20 / 30.0)
        assert ws.delta("mesh_tpu_serve_requests_total",
                        window_s=500.0, now=70.0) == 40

    def test_stage_breakdown_windowed(self):
        _, ws = self._filled()
        brk = ws.stage_breakdown(window_s=30.0, now=70.0)
        assert ("queue", "xla") in brk
        row = brk[("queue", "xla")]
        assert row["count"] == 20
        assert 0.05 < row["p99_s"] <= 0.1

    def test_percentile_none_without_observations(self):
        reg = Registry()
        ws = WindowedSeries(registry=reg, clock=FakeClock(0.0))
        assert ws.percentile("mesh_tpu_request_stage_seconds", 0.99,
                             window_s=60.0, now=1.0) is None

    def test_sample_ring_boundary_semantics(self):
        ring = SampleRing(history=16)
        for t, v in ((0.0, 0), (10.0, 5), (20.0, 9), (30.0, 12)):
            ring.append(t, (v,))
        # window [10, 30]: boundary is the sample AT 10
        assert ring.deltas(20.0, now=30.0) == (7,)
        # window longer than history: oldest sample is the baseline
        assert ring.deltas(500.0, now=30.0) == (12,)


# ---------------------------------------------------------------------------
# prof diff attribution (fault-injected per-stage slowdowns)


def _workload(led, clk, n=24, **stage_s):
    for _ in range(n):
        _serve_one(led, clk, **stage_s)


class TestProfAttribution:

    def test_identical_loads_pass(self):
        led, clk = _fake_ledger()
        _workload(led, clk)
        stats = prof.stats_from_records(led.records())
        rc, lines = prof.diff(stats, stats)
        assert rc == 0
        assert any(line.startswith("ok   p99") for line in lines)

    def test_diff_names_the_slow_stage_queue(self):
        a_led, a_clk = _fake_ledger()
        _workload(a_led, a_clk)
        b_led, b_clk = _fake_ledger()
        _workload(b_led, b_clk, queue_s=0.050)      # sleep in queue
        a = prof.stats_from_records(a_led.records())
        b = prof.stats_from_records(b_led.records())
        rc, lines = prof.diff(a, b)
        assert rc == 1
        fails = [line for line in lines if line.startswith("FAIL")]
        assert fails and all("stage 'queue'" in line for line in fails)

    def test_diff_names_the_slow_stage_dispatch(self):
        a_led, a_clk = _fake_ledger()
        _workload(a_led, a_clk)
        b_led, b_clk = _fake_ledger()
        _workload(b_led, b_clk, dispatch_s=0.050)   # sleep in dispatch
        a = prof.stats_from_records(a_led.records())
        b = prof.stats_from_records(b_led.records())
        rc, lines = prof.diff(a, b)
        assert rc == 1
        assert any("stage 'dispatch'" in line for line in lines
                   if line.startswith("FAIL"))

    def test_small_absolute_deltas_never_fail(self):
        """Large relative but sub-min_delta_s absolute growth stays rc 0
        — noise at the 10 us scale must not gate CI."""
        a = {"stages": {"queue": {"count": 5, "p50_s": 2e-5, "p99_s": 2e-5,
                                  "mean_s": 2e-5}},
             "total": {"count": 5, "p50_s": 2e-5, "p99_s": 2e-5},
             "backends": {"xla": 5}}
        b = json.loads(json.dumps(a))
        for blk in (b["stages"]["queue"], b["total"]):
            blk["p50_s"] = blk["p99_s"] = 6e-5      # 3x but only +40 us
        rc, _ = prof.diff(a, b)
        assert rc == 0

    def test_stats_from_records_requires_stage_rows(self):
        with pytest.raises(prof.ProfError):
            prof.stats_from_records([{"tenant": "t"}])


# ---------------------------------------------------------------------------
# CLI rc matrix (subprocess, no jax backend init)


def _prof_cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "prof"] + list(argv),
        capture_output=True, text=True, env=env, cwd=_REPO, timeout=120)


class TestProfCLI:

    @pytest.fixture()
    def dumps(self, tmp_path):
        """baseline.jsonl, slow_dispatch.jsonl, garbage.txt"""
        a_led, a_clk = _fake_ledger()
        _workload(a_led, a_clk)
        b_led, b_clk = _fake_ledger()
        _workload(b_led, b_clk, dispatch_s=0.060)
        a_path = tmp_path / "baseline.jsonl"
        b_path = tmp_path / "slow_dispatch.jsonl"
        assert a_led.dump_jsonl(str(a_path)) == 24
        assert b_led.dump_jsonl(str(b_path)) == 24
        garbage = tmp_path / "garbage.txt"
        garbage.write_text("this is not a profile {\n")
        return str(a_path), str(b_path), str(garbage)

    def test_top_rc0_prints_stage_table(self, dumps):
        a_path, _, _ = dumps
        res = _prof_cli("top", a_path)
        assert res.returncode == 0, res.stderr
        for needle in ("queue", "dispatch", "respond", "TOTAL",
                       "backends: xla=24"):
            assert needle in res.stdout

    def test_top_json_round_trips(self, dumps):
        a_path, _, _ = dumps
        res = _prof_cli("top", a_path, "--json")
        assert res.returncode == 0, res.stderr
        stats = json.loads(res.stdout)
        assert stats["total"]["count"] == 24
        assert set(stats["stages"]) == {"queue", "dispatch", "device",
                                        "respond"}

    def test_diff_same_source_rc0(self, dumps):
        a_path, _, _ = dumps
        res = _prof_cli("diff", a_path, a_path)
        assert res.returncode == 0, res.stderr
        assert "prof diff: OK" in res.stdout

    def test_diff_regression_rc1_names_stage(self, dumps):
        a_path, b_path, _ = dumps
        res = _prof_cli("diff", a_path, b_path)
        assert res.returncode == 1
        assert "prof diff: REGRESSION" in res.stdout
        assert "stage 'dispatch'" in res.stdout

    def test_diff_loose_tol_rc0(self, dumps):
        a_path, b_path, _ = dumps
        res = _prof_cli("diff", a_path, b_path, "--tol", "1000")
        assert res.returncode == 0, res.stderr

    def test_unreadable_input_rc2(self, dumps, tmp_path):
        a_path, _, garbage = dumps
        assert _prof_cli("top", garbage).returncode == 2
        missing = str(tmp_path / "never_written.jsonl")
        assert _prof_cli("diff", a_path, missing).returncode == 2
