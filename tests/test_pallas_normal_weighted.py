"""Normal-weighted Pallas kernel parity (interpret mode, CPU).

Same bar as the other Pallas kernels: must agree with the plain-JAX
normal_weighted path on the blended cost everywhere, and on faces up to
exact cost ties.
"""

import numpy as np

from mesh_tpu.geometry import tri_normals
from mesh_tpu.query import nearest_normal_weighted
from mesh_tpu.query.pallas_normal_weighted import nearest_normal_weighted_pallas
from tests.fixtures import icosphere


def _blended_cost(v, f, points, normals, face, point, eps):
    tn = np.asarray(tri_normals(v.astype(np.float32), f))
    d = np.linalg.norm(points - point, axis=-1)
    pen = eps * (1.0 - np.sum(normals * tn[face], axis=-1))
    return d + pen


class TestNormalWeightedPallas:
    def _case(self, n=500, seed=0):
        v, f = icosphere(2)
        rng = np.random.RandomState(seed)
        points = rng.randn(n, 3).astype(np.float32) * 0.8
        normals = rng.randn(n, 3).astype(np.float32)
        normals /= np.linalg.norm(normals, axis=1, keepdims=True)
        return v.astype(np.float32), f.astype(np.int32), points, normals

    def test_matches_xla_path(self):
        v, f, points, normals = self._case()
        eps = 0.1
        face_p, point_p = nearest_normal_weighted_pallas(
            v, f, points, normals, eps=eps, tile_q=128, tile_f=256,
            interpret=True,
        )
        face_x, point_x = nearest_normal_weighted(v, f, points, normals, eps=eps)
        cost_p = _blended_cost(v, f, points, normals,
                               np.asarray(face_p), np.asarray(point_p), eps)
        cost_x = _blended_cost(v, f, points, normals,
                               np.asarray(face_x), np.asarray(point_x), eps)
        np.testing.assert_allclose(cost_p, cost_x, atol=1e-5, rtol=1e-5)
        assert (np.asarray(face_p) == np.asarray(face_x)).mean() > 0.95

    def test_eps_zero_reduces_to_closest_point(self):
        from mesh_tpu.query import closest_faces_and_points

        v, f, points, normals = self._case(n=300, seed=1)
        face, point = nearest_normal_weighted_pallas(
            v, f, points, normals, eps=0.0, tile_q=128, tile_f=256,
            interpret=True,
        )
        ref = closest_faces_and_points(v, f, points)
        d_p = np.linalg.norm(points - np.asarray(point), axis=-1)
        d_r = np.sqrt(np.asarray(ref["sqdist"]))
        np.testing.assert_allclose(d_p, d_r, atol=1e-5, rtol=1e-4)

    def test_eps_flips_winner_toward_aligned_normal(self):
        # reference semantic test (tests/test_aabb_n_tree.py:41-52): with a
        # large eps the chosen face aligns with the query normal even when a
        # nearer face exists
        v, f, _, _ = self._case()
        point = np.array([[0.0, 0.0, 1.05]], np.float32)  # just above +z pole
        toward_x = np.array([[1.0, 0.0, 0.0]], np.float32)
        f0, _ = nearest_normal_weighted_pallas(
            v, f, point, toward_x, eps=0.0, tile_q=128, tile_f=256,
            interpret=True,
        )
        f_big, _ = nearest_normal_weighted_pallas(
            v, f, point, toward_x, eps=5.0, tile_q=128, tile_f=256,
            interpret=True,
        )
        tn = np.asarray(tri_normals(v, f))
        assert tn[int(f_big[0])] @ toward_x[0] > tn[int(f0[0])] @ toward_x[0]
