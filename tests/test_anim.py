"""mesh_tpu.anim: dynamic meshes — refit, delta tier, avatar sessions.

The load-bearing claims under test (ISSUE 19 acceptance):

- a frozen-order refit answers queries BIT-IDENTICALLY to a fresh
  rebuild of the same deformed geometry — on smooth deforms and on
  degenerate collapses (exact distances either way);
- refitting the keyframe geometry reproduces the build boxes bit for
  bit, so the inflation ratio anchors at exactly 1.0;
- the box-inflation bound deterministically trips a rebuild through
  the digest-keyed cache on an adversarial stretch, and the
  ``MESH_TPU_ANIM_REFIT_MAX_INFLATION`` pin moves the crossover;
- the delta tier's manifest tolerance is a TRUE reconstruction bound,
  frame by frame, block by block;
- a session teardown without drain closes the in-flight frame's ledger
  record with outcome ``cancelled`` (the LED001 contract, same shape
  as the PR 14 serve stop-leak regression);
- ``MESH_TPU_ANIM=0`` serves frames through the cold pre-anim path
  (action ``cold``, no ``refit`` stage stamped, same answers).
"""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from mesh_tpu import obs                                   # noqa: E402
from mesh_tpu.accel.build import (                         # noqa: E402
    build_bvh,
    clear_index_cache,
    get_index,
)
from mesh_tpu.accel.traverse import bvh_closest_point      # noqa: E402
from mesh_tpu.anim import (                                # noqa: E402
    AvatarSession,
    RefitState,
    SessionClosed,
    box_measure,
    refit_bvh,
    refit_max_inflation,
)
from mesh_tpu.obs.ledger import get_ledger                 # noqa: E402
from mesh_tpu.sphere import _icosphere                     # noqa: E402
from mesh_tpu.store import MeshStore, clear_page_cache     # noqa: E402
from mesh_tpu.store import deltas                          # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_caches():
    clear_index_cache()
    clear_page_cache()
    yield
    clear_index_cache()
    clear_page_cache()


@pytest.fixture
def store(tmp_path, monkeypatch):
    root = str(tmp_path / "store")
    monkeypatch.setenv("MESH_TPU_STORE_DIR", root)
    return MeshStore(root)


def _sphere(subdiv=2):
    v, f = _icosphere(subdiv)
    return np.asarray(v, np.float32), np.asarray(f, np.int32)


def _queries(n=48, seed=0):
    rng = np.random.RandomState(seed)
    pts = rng.randn(n, 3)
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    pts *= 1.0 + 0.1 * rng.randn(n, 1)
    return np.asarray(pts, np.float32)


def _deform(v, seed, amp=0.05):
    rng = np.random.RandomState(seed)
    return np.asarray(v + amp * rng.randn(*v.shape), np.float32)


# ---------------------------------------------------------------------------
# refit: exactness and the inflation anchor


class TestRefitExactness:

    def test_keyframe_refit_reproduces_build_boxes_bitwise(self):
        v, f = _sphere()
        base = build_bvh(v, f)
        refit, info = refit_bvh(base, v, f)
        for key in ("node_lo", "node_hi"):
            assert np.array_equal(np.asarray(base.arrays[key]),
                                  np.asarray(refit.arrays[key]))
        # shared-layout arrays are the SAME objects, not copies — that
        # identity is what keeps the compiled plan reused across frames
        for key in ("order", "node_skip", "node_leaf", "center"):
            assert refit.arrays[key] is base.arrays[key]
        assert refit.digest == base.digest
        assert info["box_measure"] == pytest.approx(box_measure(
            base.arrays["node_lo"], base.arrays["node_hi"]))

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_random_deform_traversal_bit_identical_to_rebuild(self, seed):
        v, f = _sphere()
        base = build_bvh(v, f)
        v2 = _deform(v, seed)
        refit, _ = refit_bvh(base, v2, f)
        fresh = build_bvh(v2, f)
        pts = _queries(seed=seed)
        out_r = bvh_closest_point(v2, f, pts, index=refit)
        out_b = bvh_closest_point(v2, f, pts, index=fresh)
        for key in ("face", "point", "sqdist"):
            assert np.array_equal(np.asarray(out_r[key]),
                                  np.asarray(out_b[key])), key

    @pytest.mark.parametrize("mode", ["collapse", "planar", "needle"])
    def test_degenerate_deform_distances_stay_exact(self, mode):
        """Degenerate deforms (all vertices coincident, flattened to a
        plane, stretched to a needle) massively inflate the frozen-order
        boxes — pruning decays, EXACTNESS must not.  Closest faces can
        legitimately tie under a collapse, so the bitwise claim is on
        the squared distances (the min over an identical multiset)."""
        v, f = _sphere()
        base = build_bvh(v, f)
        if mode == "collapse":
            v2 = np.zeros_like(v)
        elif mode == "planar":
            v2 = v.copy()
            v2[:, 2] = 0.0
        else:
            v2 = v * np.asarray([[1e3, 1e-3, 1e-3]], np.float32)
        refit, _ = refit_bvh(base, v2, f)
        fresh = build_bvh(v2, f)
        pts = _queries()
        out_r = bvh_closest_point(v2, f, pts, index=refit)
        out_b = bvh_closest_point(v2, f, pts, index=fresh)
        assert np.array_equal(np.asarray(out_r["sqdist"]),
                              np.asarray(out_b["sqdist"]))

    def test_refit_rejects_non_bvh_index(self):
        v, f = _sphere(1)
        grid = get_index(v, f, kind="grid")
        with pytest.raises(ValueError, match="bvh"):
            refit_bvh(grid, v, f)


# ---------------------------------------------------------------------------
# the inflation bound and its rebuild trip


class TestInflationTrip:

    def test_adversarial_stretch_trips_rebuild_deterministically(self):
        v, f = _sphere()
        state = RefitState(build_bvh(v, f), f)
        obs.reset()
        # frame 1: a gentle deform refits and tracks a finite ratio
        _idx, action = state.advance(_deform(v, 7, amp=0.01))
        assert action == "refit"
        assert state.inflation >= 1.0
        # frame 2: an adversarial 20x stretch inflates the frozen-order
        # boxes far past any sane crossover — must rebuild and re-anchor
        stretched = np.asarray(v * 20.0, np.float32)
        idx, action = state.advance(stretched, max_inflation=1.5)
        assert action == "rebuild"
        assert state.inflation == 1.0
        assert state.rebuilds == 1 and state.refits == 1
        from mesh_tpu.obs.metrics import REGISTRY

        assert REGISTRY.get("mesh_tpu_anim_rebuilds_total").value(
            reason="inflation") == 1
        # the rebuilt index IS the digest-cache entry for the stretched
        # geometry: replaying the frame rebuilds nothing
        assert idx is get_index(stretched, f, kind="bvh",
                                leaf_size=state.leaf_size)
        # and refitting from the re-anchored reference is clean again
        _idx, action = state.advance(
            np.asarray(stretched * 1.001, np.float32), max_inflation=1.5)
        assert action == "refit"

    def test_env_pin_moves_the_crossover(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_ANIM_REFIT_MAX_INFLATION", "3.5")
        assert refit_max_inflation() == pytest.approx(3.5)
        v, f = _sphere(1)
        state = RefitState(build_bvh(v, f), f)
        # a pin high above the measured ratio keeps even a big deform
        # on the refit path
        monkeypatch.setenv("MESH_TPU_ANIM_REFIT_MAX_INFLATION", "4.0")
        _idx, action = state.advance(np.asarray(v * 1.5, np.float32))
        assert action == "refit"


# ---------------------------------------------------------------------------
# delta tier: the manifest tolerance is a true bound


class TestDeltaTrueBound:

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_manifest_tolerance_bounds_reconstruction(self, store, seed):
        rng = np.random.default_rng(seed)
        v, f = _sphere()
        scale = float(rng.uniform(0.01, 50.0))
        v = np.asarray(v * scale, np.float32)
        digest = store.ingest(v, f)
        frames = [np.asarray(v + rng.normal(
            scale=0.05 * scale, size=v.shape), np.float32)
            for _ in range(4)]
        manifest = deltas.write_sequence(store, digest, "walk", frames)
        assert manifest["schema_version"] == 2
        assert manifest["kind"] == "anim_sequence"
        for block in manifest["blocks"]:
            k = block["frame"]
            got, faces, _m = deltas.read_frame(store, digest, "walk", k)
            assert np.array_equal(faces, f)
            err = float(np.max(np.abs(
                got.astype(np.float64) - frames[k].astype(np.float64))))
            assert err <= block["tolerance"], \
                "frame %d: %.3g > stated %.3g" % (
                    k, err, block["tolerance"])
        assert deltas.sequence_tolerance(manifest) == pytest.approx(
            max(b["tolerance"] for b in manifest["blocks"]))
        assert store.verify(digest) == []

    def test_anim_tier_opens_through_the_store(self, store):
        v, f = _sphere(1)
        digest = store.ingest(v, f)
        frames = [np.asarray(v * 1.01, np.float32)]
        deltas.write_sequence(store, digest, "wave", frames)
        mesh = store.open(digest, tier="anim:wave:0")
        assert mesh.tier == "anim:wave:0"
        tol = deltas.sequence_tolerance(
            store.sequence_manifest(digest, "wave"))
        assert float(np.max(np.abs(
            mesh.v.astype(np.float64)
            - frames[0].astype(np.float64)))) <= tol


# ---------------------------------------------------------------------------
# avatar sessions


class TestAvatarSession:

    def test_frame_refits_and_answers_exactly(self):
        v, f = _sphere()
        from mesh_tpu import Mesh

        pts = _queries()
        with AvatarSession(Mesh(v=v, f=f)) as sess:
            v2 = _deform(v, 11)
            out = sess.frame(vertices=v2, points=pts)
            assert out["action"] == "refit"
            assert out["inflation"] >= 1.0
            fresh = build_bvh(v2, f)
            ref = bvh_closest_point(v2, f, pts, index=fresh)
            for key in ("points", "sqdist"):
                ref_key = "point" if key == "points" else key
                assert np.array_equal(np.asarray(out[key]),
                                      np.asarray(ref[ref_key])), key
            assert sess.routing_key is not None
            row = [r for r in get_ledger().records()
                   if r.get("tenant") == sess.session_id][-1]
            assert row["outcome"] == "ok"
            assert "refit" in row["stages"]

    def test_delta_and_vertices_are_exclusive(self):
        v, f = _sphere(1)
        from mesh_tpu import Mesh

        with AvatarSession(Mesh(v=v, f=f)) as sess:
            with pytest.raises(ValueError, match="exactly one"):
                sess.frame()
            with pytest.raises(ValueError, match="exactly one"):
                sess.frame(delta=np.zeros_like(v), vertices=v)
            with pytest.raises(ValueError, match="shape"):
                sess.frame(delta=np.zeros((3, 3), np.float32))

    def test_kill_switch_serves_cold_frames(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_ANIM", "0")
        v, f = _sphere()
        from mesh_tpu import Mesh

        pts = _queries()
        with AvatarSession(Mesh(v=v, f=f)) as sess:
            v2 = _deform(v, 13)
            out = sess.frame(vertices=v2, points=pts)
            assert out["action"] == "cold"
            assert out["inflation"] is None
            # the cold path is the pre-anim path bit for bit: a digest-
            # keyed get_index build, traversed exactly
            ref = bvh_closest_point(
                v2, f, pts, index=get_index(v2, f, kind="bvh"))
            assert np.array_equal(np.asarray(out["sqdist"]),
                                  np.asarray(ref["sqdist"]))
            row = [r for r in get_ledger().records()
                   if r.get("tenant") == sess.session_id][-1]
            assert "refit" not in row["stages"]

    def test_stop_without_drain_closes_ledger_record_cancelled(self):
        """Teardown leak regression, the AvatarSession twin of
        test_serve.py::test_service_stop_without_drain_closes_ledger_
        records: a client that vanishes mid-frame must leave the frame's
        ledger record CLOSED with outcome ``cancelled`` (LED001), never
        dangling open."""
        v, f = _sphere(1)
        from mesh_tpu import Mesh

        sess = AvatarSession(Mesh(v=v, f=f),
                             session_id="anim-stop-no-drain")
        sess.hold()             # park the frame before record close
        done = threading.Event()

        def run():
            sess.frame(vertices=_deform(v, 17), points=_queries(8))
            done.set()

        t = threading.Thread(target=run)
        t.start()
        # deterministic: wait until the frame is computed and parked
        for _ in range(2000):
            if sess._inflight:
                break
            t.join(0.005)
        assert sess._inflight, "frame never reached the hold fence"
        sess.stop(drain=False)
        t.join(10.0)
        assert done.is_set()
        rows = [r for r in get_ledger().records()
                if r.get("tenant") == "anim-stop-no-drain"]
        assert len(rows) == 1
        assert rows[0]["outcome"] == "cancelled"
        with pytest.raises(SessionClosed):
            sess.frame(vertices=v)

    def test_deadline_miss_counts_and_closes_deadline(self):
        v, f = _sphere(1)
        from mesh_tpu import Mesh

        with AvatarSession(Mesh(v=v, f=f)) as sess:
            out = sess.frame(vertices=_deform(v, 19), points=_queries(8),
                             deadline_s=1e-9)
            assert out["deadline_missed"]
            assert sess.deadline_misses == 1
            row = [r for r in get_ledger().records()
                   if r.get("tenant") == sess.session_id][-1]
            assert row["outcome"] == "deadline"


# ---------------------------------------------------------------------------
# end-to-end: a multi-frame stream off the store (minutes-scale on CPU)


@pytest.mark.slow
def test_session_stream_from_store_end_to_end(store):
    """Full avatar stream: keyframe ingested, deltas published to the
    sequence tier, session opened from the digest, every frame decoded
    from the store and served with answers bit-identical to a fresh
    rebuild of the decoded geometry, metrics and stats consistent."""
    v, f = _sphere(3)
    digest = store.ingest(v, f)
    rng = np.random.default_rng(23)
    frames = [np.asarray(v * (1.0 + 0.02 * (k + 1))
                         + rng.normal(scale=0.01, size=v.shape),
                         np.float32)
              for k in range(6)]
    deltas.write_sequence(store, digest, "run", frames)
    pts = _queries(64, seed=5)
    with AvatarSession(digest=digest, store=store) as sess:
        for k in range(len(frames)):
            decoded, _faces, _m = deltas.read_frame(store, digest,
                                                    "run", k)
            out = sess.frame(vertices=decoded, points=pts)
            assert out["action"] in ("refit", "rebuild")
            fresh = build_bvh(decoded, f)
            ref = bvh_closest_point(decoded, f, pts, index=fresh)
            assert np.array_equal(np.asarray(out["sqdist"]),
                                  np.asarray(ref["sqdist"])), (
                "frame %d diverged" % k)
        stats = sess.stats()
        assert stats["frames"] == len(frames)
        assert stats["refits"] + stats["rebuilds"] >= len(frames)
        assert stats["routing_key"] is not None
    rows = [r for r in get_ledger().records()
            if r.get("tenant") == sess.session_id]
    assert rows and all(r["outcome"] == "ok" for r in rows)
