"""Doc lint: every metrics series the code can create must be documented.

Scans mesh_tpu/ for registry constructor calls (``counter("mesh_tpu_..."``
etc.) and serve-tier span names, expands the ``{a,b,c}`` brace shorthand
the doc table uses, and fails with the exact list of undocumented names.
This keeps doc/observability.md's series table from silently rotting as
instrumentation is added.
"""

import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "mesh_tpu")
_DOC = os.path.join(_REPO, "doc", "observability.md")

# registry constructor with a literal name; DOTALL so the call may wrap
_SERIES_RE = re.compile(
    r'(?:counter|gauge|histogram)\(\s*"(mesh_tpu_[a-z0-9_]+)"', re.DOTALL)
# serve-tier span names (obs_span / TRACER.span / timed_span)
_SPAN_RE = re.compile(
    r'(?:obs_span|span|timed_span)\(\s*"(serve\.[a-z0-9_.]+)"', re.DOTALL)
# jax_bridge registers series through helper indirection -> any literal
_BRIDGE_RE = re.compile(r'"(mesh_tpu_[a-z0-9_]+)"')

# doc-side names, allowing the {a,b,c} brace shorthand used in the table
_DOC_NAME_RE = re.compile(r"(?:mesh_tpu|serve\.)(?:[a-z0-9_.]|\{[a-z0-9_,]+\})+")


def _python_files():
    for dirpath, _, filenames in os.walk(_PKG):
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _code_names():
    names = set()
    for path in _python_files():
        with open(path) as fh:
            src = fh.read()
        names.update(_SERIES_RE.findall(src))
        names.update(_SPAN_RE.findall(src))
        if os.path.basename(path) == "jax_bridge.py":
            names.update(_BRIDGE_RE.findall(src))
    return names


def _expand_braces(token):
    """``a_{x,y}_b`` -> {a_x_b, a_y_b} (one level is all the doc uses,
    but recurse anyway)."""
    match = re.search(r"\{([a-z0-9_,]+)\}", token)
    if not match:
        return {token}
    out = set()
    for alt in match.group(1).split(","):
        out |= _expand_braces(token[:match.start()] + alt
                              + token[match.end():])
    return out


def _doc_names():
    with open(_DOC) as fh:
        text = fh.read()
    names = set()
    for token in _DOC_NAME_RE.findall(text):
        names |= _expand_braces(token.rstrip("."))
    return names


def test_every_code_series_is_documented():
    code = _code_names()
    # sanity: the scan itself must keep finding the core instrumentation,
    # otherwise a regex regression would vacuously pass the lint
    assert "mesh_tpu_serve_requests_total" in code
    assert "mesh_tpu_slo_burn_rate" in code
    assert "mesh_tpu_incident_dumps_total" in code
    assert "serve.request" in code

    documented = _doc_names()
    missing = sorted(code - documented)
    assert not missing, (
        "series created in code but absent from doc/observability.md: %s"
        % ", ".join(missing))


def test_brace_expansion_helper():
    assert _expand_braces("mesh_tpu_engine_plan_{hits,misses}_total") == {
        "mesh_tpu_engine_plan_hits_total",
        "mesh_tpu_engine_plan_misses_total",
    }
    assert _expand_braces("plain_name") == {"plain_name"}
