"""Doc lint: every metrics series the code can create must be documented.

Thin wrapper over the meshlint OBS rule (``mesh_tpu.analysis``) — the
regex scan this file used to carry moved into
mesh_tpu/analysis/rules/obs.py, where ``mesh-tpu lint`` enforces it as
OBS001 (doc coverage) and OBS002 (literal names).  The original test
names and their sanity anchors are preserved so the suite's coverage is
unchanged while the single source of truth is the rule pack.
"""

import os

from mesh_tpu.analysis import build_project
from mesh_tpu.analysis.rules import obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_every_code_series_is_documented():
    project, failures = build_project(_REPO)
    assert not failures, [f.render() for f in failures]
    code = obs.collect_code_names(project)
    # sanity: the scan itself must keep finding the core instrumentation,
    # otherwise a scanner regression would vacuously pass the lint
    assert "mesh_tpu_serve_requests_total" in code
    assert "mesh_tpu_slo_burn_rate" in code
    assert "mesh_tpu_incident_dumps_total" in code
    assert "serve.request" in code

    doc_text = project.doc_text("doc", "observability.md")
    assert doc_text is not None, "doc/observability.md is missing"
    documented = obs.documented_names(doc_text)
    missing = sorted(set(code) - documented)
    assert not missing, (
        "series created in code but absent from doc/observability.md: %s"
        % ", ".join(missing))


def test_brace_expansion_helper():
    assert obs.expand_braces("mesh_tpu_engine_plan_{hits,misses}_total") == {
        "mesh_tpu_engine_plan_hits_total",
        "mesh_tpu_engine_plan_misses_total",
    }
    assert obs.expand_braces("plain_name") == {"plain_name"}
