"""Device-absolute accounting (benchmarks/roofline.py)."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "benchmarks")
)
from roofline import (  # noqa: E402
    FLOPS_PER_PAIR,
    V5E_PEAK_FLOPS_VPU_F32,
    V5E_PEAK_HBM_BYTES,
    accounting,
)


def test_tpu_block_has_peaks_and_bound():
    out = accounting("closest_point", t_seconds=0.1,
                     n_pairs=262144 * 13776, n_queries=262144,
                     n_faces=13776, face_planes=19, platform="tpu")
    assert out["bound"] in ("vpu", "hbm")
    assert 0 < out["pct_vpu_f32_peak"]
    assert 0 < out["pct_hbm_peak"]
    # high-intensity streaming kernel must classify as compute-bound
    assert out["arithmetic_intensity_flops_per_byte"] > (
        V5E_PEAK_FLOPS_VPU_F32 / V5E_PEAK_HBM_BYTES
    )
    assert out["bound"] == "vpu"


def test_cpu_block_omits_peaks():
    out = accounting("ray_any_hit", t_seconds=1.0, n_pairs=1000,
                     n_queries=10, n_faces=100, platform="cpu")
    assert "pct_vpu_f32_peak" not in out
    assert out["pair_tests_per_sec"] == 1000.0


def test_low_intensity_classifies_hbm_bound():
    # one query against many faces: each 256-query tile streams all the
    # face planes for very few pair tests -> memory-bound
    out = accounting("nearest_vertex", t_seconds=0.1, n_pairs=1_000_000,
                     n_queries=1, n_faces=1_000_000, face_planes=19,
                     platform="tpu")
    assert out["bound"] == "hbm"


def test_flop_table_covers_all_kernel_kinds():
    assert set(FLOPS_PER_PAIR) == {
        "closest_point", "ray_any_hit", "alongnormal", "tri_tri",
        "tri_tri_moller", "nearest_vertex",
    }


def test_unknown_kind_raises():
    with pytest.raises(KeyError):
        accounting("nope", 1.0, 1, 1, 1)
