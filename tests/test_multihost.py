"""Multi-host sharded queries, tested with REAL processes (the reference's
no-mocks style, tests/test_meshviewer.py:52-79): two children each own a
4-device CPU platform, join one jax.distributed process group (Gloo
between them — the DCN stand-in), and run the multihost closest-point
query on a mesh spanning both."""

import os
import socket
import subprocess
import sys

import jax
import pytest

#: jax < 0.5 cannot run multi-process collectives on the CPU backend
#: ("Multiprocess computations aren't implemented on the CPU backend"),
#: so the two-host CPU stand-in below is impossible there
needs_multiprocess_cpu = pytest.mark.skipif(
    jax.__version_info__ < (0, 5, 0),
    reason="multi-process CPU collectives need jax >= 0.5",
)

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_pair(port, env):
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "_multihost_child.py"),
             str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


@needs_multiprocess_cpu
def test_two_process_closest_point():
    env = dict(os.environ)
    # the children configure their own platform before importing jax; drop
    # this session's forced single-process settings so they don't leak
    for k in ("JAX_NUM_CPU_DEVICES", "XLA_FLAGS"):
        env.pop(k, None)
    for attempt in range(3):
        procs, outs = _spawn_pair(_free_port(), env)
        if all(p.returncode == 0 for p in procs):
            break
        # _free_port closes the socket before the coordinator rebinds it;
        # a busy host can steal the port in that gap — retry on that only
        if attempt < 2 and any("already in use" in o.lower() for o in outs):
            continue
        break
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            "child %d rc=%s\n%s" % (pid, p.returncode, out[-3000:])
        )
        assert "MULTIHOST_OK process=%d" % pid in out, out[-3000:]
    # the SPMD fit step must produce the identical loss on every host
    losses = {
        line.split()[1]
        for out in outs
        for line in out.splitlines()
        if line.startswith("MULTIHOST_FIT_LOSS")
    }
    assert len(losses) == 1, "hosts disagree on the fit loss: %s" % losses
