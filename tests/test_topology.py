"""Topology tests: connectivity invariants, Loop subdivision, QSlim
(goes beyond the reference's smoke tests, tests/test_topology.py, which skip
qslim entirely)."""

import numpy as np

from mesh_tpu import Mesh
from mesh_tpu.topology import (
    get_faces_per_edge,
    get_vert_connectivity,
    get_vert_opposites_per_edge,
    get_vertices_per_edge,
    loop_subdivider,
    qslim_decimator,
    vertices_to_edges_matrix,
)
from mesh_tpu.topology.connectivity import edge_topology_arrays

from .fixtures import box, icosphere


class TestConnectivity:
    def test_box_euler(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        vpe = get_vertices_per_edge(m)
        assert vpe.shape == (18, 2)  # V - E + F = 2 -> E = 18
        fpe = get_faces_per_edge(m)
        assert fpe.shape == (18, 2)
        vc = get_vert_connectivity(m)
        assert vc.shape == (8, 8)
        assert (vc.todense() > 0).sum() == 36  # 2 * E directed

    def test_opposites(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        vo = get_vert_opposites_per_edge(m)
        assert len(vo) == 18
        # every closed-mesh edge has exactly two opposite vertices
        assert all(len(opp) == 2 for opp in vo.values())

    def test_edges_matrix(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        M = vertices_to_edges_matrix(m, want_xyz=True)
        e = M.dot(v.flatten()).reshape(-1, 3)
        vpe = np.asarray(get_vertices_per_edge(m), dtype=np.int64)
        np.testing.assert_allclose(e, v[vpe[:, 0]] - v[vpe[:, 1]])

    def test_edge_topology_arrays(self):
        v, f = box()
        topo = edge_topology_arrays(f, len(v))
        assert topo["edges"].shape == (18, 2)
        assert (topo["edge_opposites"] >= 0).all()  # closed mesh: no pads
        assert (topo["faces_per_edge"] >= 0).all()

    def test_cache_roundtrip(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        first = get_vertices_per_edge(m)
        second = get_vertices_per_edge(m)  # served from disk cache
        np.testing.assert_array_equal(first, second)


class TestLoopSubdivision:
    def test_box_counts(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        xform = loop_subdivider(m)
        sub = xform(m)
        assert sub.v.shape == (8 + 18, 3)   # verts + edge midpoints
        assert sub.f.shape == (48, 3)       # 4x faces
        # subdivision surface shrinks toward the interior: all within box
        assert np.abs(sub.v).max() <= 0.5 + 1e-9

    def test_sphere_stays_spherical(self):
        v, f = icosphere(1)
        m = Mesh(v=v, f=f)
        sub = loop_subdivider(m)(m)
        r = np.linalg.norm(sub.v, axis=1)
        assert r.min() > 0.7 and r.max() <= 1.0 + 1e-9

    def test_raw_array_application(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        xform = loop_subdivider(m)
        flat = xform(v.flatten())
        np.testing.assert_allclose(flat.reshape(-1, 3), xform(m).v)


class TestQslim:
    def test_decimates_to_target(self):
        v, f = icosphere(2)  # 162 verts
        m = Mesh(v=v, f=f)
        xform = qslim_decimator(m, n_verts_desired=80)
        dec = xform(m)
        assert dec.v.shape[0] <= 82
        assert dec.f.min() >= 0 and dec.f.max() < dec.v.shape[0]
        # decimated sphere still roughly spherical
        r = np.linalg.norm(dec.v, axis=1)
        assert r.min() > 0.6 and r.max() < 1.3

    def test_factor(self):
        v, f = icosphere(1)
        m = Mesh(v=v, f=f)
        dec = qslim_decimator(m, factor=0.5)(m)
        assert dec.v.shape[0] <= 0.55 * v.shape[0] + 2

    def test_smpl_scale_fast_and_faithful(self):
        """The reference skips its qslim test as 'Too long...'
        (reference tests/test_topology.py:15); the vectorized quadric
        pipeline here decimates an SMPL-sized mesh in seconds, so run it
        for real: 6890 verts -> ~700, bounded runtime, bounded surface
        error, no degenerate output faces."""
        import time

        from mesh_tpu.models.body_model import smpl_sized_sphere
        from mesh_tpu.query import closest_faces_and_points
        from mesh_tpu.topology.decimation import qslim_decimator_fast

        v, f = smpl_sized_sphere()
        m = Mesh(v=v, f=f)
        # process_time: immune to machine load (the suite may share the box
        # with benchmark runs), still fails on a complexity regression
        t0 = time.process_time()
        dec = qslim_decimator_fast(m, n_verts_desired=700)
        elapsed = time.process_time() - t0
        assert elapsed < 30, "decimation burned %.1fs CPU" % elapsed
        assert dec.v.shape[0] <= 720
        # no face may collapse to a repeated vertex
        df = np.asarray(dec.f, np.int64)
        assert (df[:, 0] != df[:, 1]).all()
        assert (df[:, 1] != df[:, 2]).all()
        assert (df[:, 2] != df[:, 0]).all()
        # surviving surface stays near the original: every original vertex
        # has a decimated face within a few percent of the unit radius
        res = closest_faces_and_points(
            dec.v.astype(np.float32), df.astype(np.int32),
            np.asarray(v[::13], np.float32),
        )
        assert float(np.sqrt(np.asarray(res["sqdist"])).max()) < 0.08


class TestProcessing:
    def test_subdivide_triangles(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        m.subdivide_triangles()
        assert m.v.shape == (8 + 12, 3)
        assert m.f.shape == (36, 3)

    def test_keep_vertices(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        m.keep_vertices([0, 1, 2, 3])  # bottom face only
        assert m.v.shape == (4, 3)
        assert (m.f < 4).all()
        assert m.f.shape[0] == 2  # only the z=-0.5 faces survive

    def test_flip_faces(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        m.flip_faces()
        np.testing.assert_array_equal(m.f, f[:, ::-1])

    def test_concatenate(self):
        v, f = box()
        m1 = Mesh(v=v, f=f)
        m2 = Mesh(v=v + 5.0, f=f)
        m1.concatenate_mesh(m2)
        assert m1.v.shape == (16, 3)
        assert m1.f.shape == (24, 3)
        assert m1.f.max() == 15

    def test_uniquified(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        u = m.uniquified_mesh()
        assert u.v.shape == (36, 3)
        np.testing.assert_array_equal(u.f, np.arange(36).reshape(-1, 3))

    @staticmethod
    def _tri_set(verts, faces):
        """Triangles as an order-independent set of corner-point tuples."""
        verts = np.asarray(verts)
        return {
            tuple(sorted(map(tuple, verts[np.asarray(face, np.int64)])))
            for face in faces
        }

    def test_remove_faces_drops_unreferenced_vertices(self):
        # reference processing.py:67-95: faces go, orphaned vertices go,
        # surviving face indices remap densely, fc rows follow the faces
        v, f = box()
        m = Mesh(v=v, f=f)
        m.set_face_colors(np.tile([1.0, 0.0, 0.0], (len(f), 1)))
        # keep faces 2 and 3 only: their vertices keep their original
        # (non-prefix) ids, so the dense remap genuinely renumbers —
        # keeping a vertex-id prefix would make the remap the identity
        keep = [2, 3]
        drop = [i for i in range(len(f)) if i not in keep]
        before = self._tri_set(v, f[keep])
        kept_ids = np.unique(f[keep])
        assert kept_ids.min() > 0                  # non-identity remap
        m.remove_faces(drop)
        assert self._tri_set(m.v, m.f) == before   # surviving geometry
        assert m.f.shape[0] == 2
        assert m.fc.shape[0] == 2
        assert len(m.v) == len(kept_ids)           # orphans dropped
        assert m.f.max() == len(m.v) - 1           # dense remap
        assert len(np.unique(m.f)) == len(m.v)

    def test_reorder_vertices_preserves_geometry(self):
        # new_ordering[i] = j means vertex i becomes the j-th vertex
        # (reference processing.py:171-186); triangles must be unchanged
        # as point sets
        rng = np.random.RandomState(0)
        v, f = box()
        m = Mesh(v=v, f=f)
        order = rng.permutation(len(v))
        tris_before = self._tri_set(v, f)
        m.reorder_vertices(order)
        np.testing.assert_allclose(np.asarray(m.v)[order], v)
        assert self._tri_set(m.v, m.f) == tris_before

    def test_rotate_scale_translate(self):
        v, f = box()
        m = Mesh(v=v, f=f)
        # axis-angle pi/2 about z, as the reference feeds cv2.Rodrigues
        m.rotate_vertices(np.array([0.0, 0.0, np.pi / 2]))
        np.testing.assert_allclose(
            np.asarray(m.v), np.stack([-v[:, 1], v[:, 0], v[:, 2]], axis=1),
            atol=1e-7,
        )
        m2 = Mesh(v=v, f=f)
        R = np.array([[0, -1, 0], [1, 0, 0], [0, 0, 1]], np.float64)
        m2.rotate_vertices(R)                  # matrix input, same result
        np.testing.assert_allclose(np.asarray(m.v), np.asarray(m2.v),
                                   atol=1e-7)
        m2.scale_vertices(2.0).translate_vertices([1.0, 0.0, 0.0])
        np.testing.assert_allclose(
            np.asarray(m2.v),
            2.0 * np.stack([-v[:, 1], v[:, 0], v[:, 2]], axis=1)
            + [1.0, 0.0, 0.0],
            atol=1e-6,
        )

    def test_point_cloud_and_reset_face_normals(self):
        v, f = box()
        m = Mesh(v=v, f=f, vc="SteelBlue")
        pc = m.point_cloud()
        assert len(pc.f) == 0
        np.testing.assert_allclose(pc.v, m.v)
        assert pc.vc.shape == m.vc.shape       # colors survive
        m.reset_face_normals()
        np.testing.assert_array_equal(m.fn, m.f)
        assert hasattr(m, "vn")                # implied reset_normals ran
