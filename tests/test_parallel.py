"""Multi-device sharding tests on the 8-device virtual CPU mesh
(conftest.py forces JAX_PLATFORMS=cpu with 8 devices)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mesh_tpu.parallel import (
    init_fit_state,
    make_device_mesh,
    make_fit_step,
    sharded_batched_vert_normals,
    sharded_closest_faces_and_points,
)
from mesh_tpu.geometry import vert_normals
from mesh_tpu.query import closest_faces_and_points

from .fixtures import icosphere

needs_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@needs_devices
class TestShardedQueries:
    def test_closest_point_matches_single_device(self):
        rng = np.random.RandomState(0)
        v, f = icosphere(2)
        points = rng.randn(1000, 3).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        sharded = sharded_closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, mesh, chunk=128
        )
        single = closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, chunk=128
        )
        np.testing.assert_allclose(
            sharded["sqdist"], np.asarray(single["sqdist"]), atol=1e-6
        )
        np.testing.assert_allclose(
            sharded["point"], np.asarray(single["point"]), atol=1e-5
        )
        # faces can differ only at exact ties; parts/points must agree
        agree = sharded["face"] == np.asarray(single["face"])
        assert agree.mean() > 0.99

    def test_non_divisible_query_count(self):
        rng = np.random.RandomState(1)
        v, f = icosphere(1)
        points = rng.randn(37, 3).astype(np.float32)  # 37 % 8 != 0
        mesh = make_device_mesh(8, ("dp",))
        out = sharded_closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, mesh, chunk=16
        )
        assert out["face"].shape == (37,)

    def test_batched_normals_sharded(self):
        rng = np.random.RandomState(2)
        v, f = icosphere(1)
        batch = (v[None] + 0.01 * rng.randn(16, *v.shape)).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        out = np.asarray(
            sharded_batched_vert_normals(batch, f.astype(np.int32), mesh)
        )
        expected = np.asarray(
            vert_normals(jnp.asarray(batch), jnp.asarray(f, jnp.int32))
        )
        np.testing.assert_allclose(out, expected, atol=1e-6)


@needs_devices
class TestDistributedFit:
    def test_fit_step_runs_on_2d_mesh(self):
        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(1)
        model = synthetic_body_model(
            seed=0, n_betas=4, n_joints=6, template=(v, f.astype(np.int32))
        )
        mesh = make_device_mesh(8, ("dp", "sp"), shape=(4, 2))
        rng = np.random.RandomState(0)
        target = jnp.asarray(rng.randn(8, 32, 3) * 0.5, jnp.float32)
        state, opt = init_fit_state(model, 8)
        step = make_fit_step(model, opt, mesh=mesh)
        state, loss0 = step(state, target)
        for _ in range(5):
            state, loss = step(state, target)
        assert np.isfinite(float(loss))
        assert float(loss) < float(loss0)  # optimization makes progress

    def test_fit_matches_unsharded(self):
        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(1)
        model = synthetic_body_model(
            seed=0, n_betas=4, n_joints=6, template=(v, f.astype(np.int32))
        )
        rng = np.random.RandomState(0)
        target = jnp.asarray(rng.randn(8, 32, 3) * 0.5, jnp.float32)

        mesh = make_device_mesh(8, ("dp", "sp"), shape=(4, 2))
        state_s, opt_s = init_fit_state(model, 8)
        step_s = make_fit_step(model, opt_s, mesh=mesh)
        state_s, loss_s = step_s(state_s, target)

        state_u, opt_u = init_fit_state(model, 8)
        step_u = make_fit_step(model, opt_u, mesh=None)
        state_u, loss_u = step_u(state_u, target)

        np.testing.assert_allclose(float(loss_s), float(loss_u), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(state_s.betas), np.asarray(state_u.betas), atol=1e-5
        )


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys

        sys.path.insert(0, "/root/repo")
        import importlib

        mod = importlib.import_module("__graft_entry__")
        fn, args = mod.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (4, 6890, 3)

    @needs_devices
    def test_dryrun_multichip(self):
        import importlib

        mod = importlib.import_module("__graft_entry__")
        mod.dryrun_multichip(8)
