"""Multi-device sharding tests on the 8-device virtual CPU mesh
(conftest.py forces JAX_PLATFORMS=cpu with 8 devices)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mesh_tpu.parallel import (
    init_fit_state,
    make_device_mesh,
    make_fit_step,
    sharded_batched_vert_normals,
    sharded_closest_faces_and_points,
    sharded_closest_faces_sharded_topology,
)
from mesh_tpu.geometry import vert_normals
from mesh_tpu.query import closest_faces_and_points

from .fixtures import icosphere

needs_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

#: the 8-virtual-device shard_map tests take minutes under the CPU
#: simulator; tier-1 (-m 'not slow') skips them, the full/TPU suite runs
#: them
slow_on_cpu_sim = pytest.mark.slow


@slow_on_cpu_sim
@needs_devices
class TestShardedQueries:
    def test_closest_point_matches_single_device(self):
        rng = np.random.RandomState(0)
        v, f = icosphere(2)
        points = rng.randn(1000, 3).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        sharded = sharded_closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, mesh, chunk=128
        )
        single = closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, chunk=128
        )
        np.testing.assert_allclose(
            sharded["sqdist"], np.asarray(single["sqdist"]), atol=1e-6
        )
        np.testing.assert_allclose(
            sharded["point"], np.asarray(single["point"]), atol=1e-5
        )
        # faces can differ only at exact ties; parts/points must agree
        agree = sharded["face"] == np.asarray(single["face"])
        assert agree.mean() > 0.99

    def test_face_sharded_matches_single_device(self):
        """Topology-sharded dual: triangles split across devices, winners
        merged by the cross-device argmin collective."""
        rng = np.random.RandomState(3)
        v, f = icosphere(2)
        points = rng.randn(200, 3).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        sharded = sharded_closest_faces_sharded_topology(
            v.astype(np.float32), f.astype(np.int32), points, mesh, chunk=64
        )
        single = closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, chunk=64
        )
        np.testing.assert_allclose(
            sharded["sqdist"], np.asarray(single["sqdist"]), atol=1e-6
        )
        np.testing.assert_allclose(
            sharded["point"], np.asarray(single["point"]), atol=1e-5
        )
        agree = sharded["face"] == np.asarray(single["face"])
        assert agree.mean() > 0.99

    def test_face_sharded_ring_merge_matches_gather(self):
        """The ppermute ring min-merge must produce BIT-IDENTICAL winners
        to the all-gather + argmin path, including exact-distance ties
        (both resolve to the lowest global face id)."""
        rng = np.random.RandomState(5)
        v, f = icosphere(2)
        # force cross-shard exact ties: duplicate the whole face list, so
        # every query's best face exists in two different shards
        f2 = np.concatenate([f, f]).astype(np.int32)
        points = rng.randn(300, 3).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        gather = sharded_closest_faces_sharded_topology(
            v.astype(np.float32), f2, points, mesh, chunk=64, merge="gather"
        )
        ring = sharded_closest_faces_sharded_topology(
            v.astype(np.float32), f2, points, mesh, chunk=64, merge="ring"
        )
        np.testing.assert_array_equal(ring["face"], gather["face"])
        np.testing.assert_array_equal(ring["part"], gather["part"])
        np.testing.assert_allclose(ring["sqdist"], gather["sqdist"], rtol=0)
        np.testing.assert_allclose(ring["point"], gather["point"], rtol=0)
        # and both agree with the single-device oracle
        single = closest_faces_and_points(
            v.astype(np.float32), f2, points, chunk=64
        )
        np.testing.assert_allclose(
            ring["sqdist"], np.asarray(single["sqdist"]), atol=1e-6
        )

    def test_face_sharded_ring_nan_propagates_like_gather(self):
        # a NaN vertex in ONE shard's face block must poison the merged
        # result identically in both merges (numpy argmin picks the first
        # NaN; the ring maps NaN to -inf for the same effect) — otherwise
        # the ring would leave devices holding different accumulators
        rng = np.random.RandomState(6)
        v, f = icosphere(2)
        v = v.astype(np.float32)
        v_nan = v.copy()
        # poison a vertex used by faces landing in a middle shard
        target_face = f[200]
        v_nan[target_face[0]] = np.nan
        points = rng.randn(40, 3).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        gather = sharded_closest_faces_sharded_topology(
            v_nan, f.astype(np.int32), points, mesh, chunk=64,
            merge="gather",
        )
        ring = sharded_closest_faces_sharded_topology(
            v_nan, f.astype(np.int32), points, mesh, chunk=64, merge="ring"
        )
        np.testing.assert_array_equal(
            np.isnan(ring["sqdist"]), np.isnan(gather["sqdist"])
        )
        np.testing.assert_array_equal(ring["face"], gather["face"])

    def test_face_sharded_merge_rejects_unknown(self):
        v, f = icosphere(1)
        mesh = make_device_mesh(8, ("dp",))
        with pytest.raises(ValueError, match="gather.*ring"):
            sharded_closest_faces_sharded_topology(
                v.astype(np.float32), f.astype(np.int32),
                np.zeros((4, 3), np.float32), mesh, merge="tree",
            )

    def test_face_sharded_non_divisible_face_count(self):
        # icosphere(1) has 80 faces; 80 % 8 == 0, so drop a few to force the
        # duplicate-face padding path
        rng = np.random.RandomState(4)
        v, f = icosphere(1)
        f = f[:77]                                  # 77 % 8 != 0
        points = rng.randn(50, 3).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        sharded = sharded_closest_faces_sharded_topology(
            v.astype(np.float32), f.astype(np.int32), points, mesh, chunk=16
        )
        single = closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, chunk=16
        )
        np.testing.assert_allclose(
            sharded["sqdist"], np.asarray(single["sqdist"]), atol=1e-6
        )
        assert sharded["face"].max() < 77

    def test_face_sharded_fewer_faces_than_shards(self):
        # 5 faces over 8 devices: three shards hold only padded duplicates
        rng = np.random.RandomState(5)
        v = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [1, 1, 0],
                      [2, 0, 0], [2, 1, 0], [0, 2, 0]], np.float32)
        f = np.array([[0, 1, 2], [1, 3, 2], [1, 4, 3], [4, 5, 3],
                      [2, 3, 6]], np.int32)
        points = rng.randn(13, 3).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        sharded = sharded_closest_faces_sharded_topology(
            v, f, points, mesh, chunk=8
        )
        single = closest_faces_and_points(v, f, points, chunk=8)
        np.testing.assert_allclose(
            sharded["sqdist"], np.asarray(single["sqdist"]), atol=1e-6
        )
        assert sharded["face"].max() < 5 and sharded["face"].min() >= 0

    def test_non_divisible_query_count(self):
        rng = np.random.RandomState(1)
        v, f = icosphere(1)
        points = rng.randn(37, 3).astype(np.float32)  # 37 % 8 != 0
        mesh = make_device_mesh(8, ("dp",))
        out = sharded_closest_faces_and_points(
            v.astype(np.float32), f.astype(np.int32), points, mesh, chunk=16
        )
        assert out["face"].shape == (37,)

    def test_batched_normals_sharded(self):
        rng = np.random.RandomState(2)
        v, f = icosphere(1)
        batch = (v[None] + 0.01 * rng.randn(16, *v.shape)).astype(np.float32)
        mesh = make_device_mesh(8, ("dp",))
        out = np.asarray(
            sharded_batched_vert_normals(batch, f.astype(np.int32), mesh)
        )
        expected = np.asarray(
            vert_normals(jnp.asarray(batch), jnp.asarray(f, jnp.int32))
        )
        np.testing.assert_allclose(out, expected, atol=1e-6)

    @pytest.mark.parametrize("n_meshes", [8, 5])   # even and padded splits
    def test_batched_visibility_sharded(self, n_meshes):
        # the mesh BATCH sharded over dp (P5 x P6): parity vs the
        # replicated one-dispatch batched kernel, incl. a batch size the
        # device count does not divide (pad + trim path)
        from mesh_tpu.batch import batched_vertex_visibility
        from mesh_tpu.parallel import sharded_batched_visibility

        rng = np.random.RandomState(3)
        v, f = icosphere(2)
        f = f.astype(np.int32)
        batch = (
            v[None] * (1 + 0.1 * rng.rand(n_meshes, 1, 1))
        ).astype(np.float32)
        cams = np.array([[0, 0, 4.0], [4.0, 0, 0]], np.float32)
        mesh = make_device_mesh(8, ("dp",))
        vis_s, ndc_s = sharded_batched_visibility(batch, f, cams, mesh)
        vis_r, ndc_r = batched_vertex_visibility((batch, f), cams)
        assert vis_s.shape == (n_meshes, 2, len(v))
        np.testing.assert_array_equal(vis_s, vis_r)
        np.testing.assert_allclose(ndc_s, ndc_r, atol=1e-5)


@slow_on_cpu_sim
@needs_devices
class TestDistributedFit:
    def test_fit_step_runs_on_2d_mesh(self):
        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(1)
        model = synthetic_body_model(
            seed=0, n_betas=4, n_joints=6, template=(v, f.astype(np.int32))
        )
        mesh = make_device_mesh(8, ("dp", "sp"), shape=(4, 2))
        rng = np.random.RandomState(0)
        target = jnp.asarray(rng.randn(8, 32, 3) * 0.5, jnp.float32)
        state, opt = init_fit_state(model, 8)
        step = make_fit_step(model, opt, mesh=mesh)
        state, loss0 = step(state, target)
        for _ in range(5):
            state, loss = step(state, target)
        assert np.isfinite(float(loss))
        assert float(loss) < float(loss0)  # optimization makes progress

    def test_fit_matches_unsharded(self):
        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(1)
        model = synthetic_body_model(
            seed=0, n_betas=4, n_joints=6, template=(v, f.astype(np.int32))
        )
        rng = np.random.RandomState(0)
        target = jnp.asarray(rng.randn(8, 32, 3) * 0.5, jnp.float32)

        mesh = make_device_mesh(8, ("dp", "sp"), shape=(4, 2))
        state_s, opt_s = init_fit_state(model, 8)
        step_s = make_fit_step(model, opt_s, mesh=mesh)
        state_s, loss_s = step_s(state_s, target)

        state_u, opt_u = init_fit_state(model, 8)
        step_u = make_fit_step(model, opt_u, mesh=None)
        state_u, loss_u = step_u(state_u, target)

        np.testing.assert_allclose(float(loss_s), float(loss_u), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(state_s.betas), np.asarray(state_u.betas), atol=1e-5
        )


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys

        sys.path.insert(0, "/root/repo")
        import importlib

        mod = importlib.import_module("__graft_entry__")
        fn, args = mod.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (4, 6890, 3)

    @slow_on_cpu_sim
    @needs_devices
    def test_dryrun_multichip(self):
        import importlib

        mod = importlib.import_module("__graft_entry__")
        mod.dryrun_multichip(8)


class TestLandmarkFit:
    """Landmark-anchored registration: the device-side form of the
    reference's landm_regressors (landmarks.py:45-65) driving the fit."""

    def _tiny_model(self):
        import numpy as np

        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(1)
        return synthetic_body_model(
            seed=0, n_betas=4, n_joints=6, template=(v, f.astype(np.int32))
        )

    def test_landmark_arrays_pack_regressors(self):
        import numpy as np

        from mesh_tpu.parallel import landmark_arrays

        regs = {
            "nose": (np.array([3, 7, 9]), np.array([0.2, 0.5, 0.3])),
            "chin": (np.array([1]), np.array([1.0])),
        }
        idx, bary, names = landmark_arrays(regs)
        assert idx.shape == (2, 3) and bary.shape == (2, 3)
        assert names == ["chin", "nose"]  # the pairing order, returned
        # sorted order: chin first, zero-padded
        np.testing.assert_array_equal(np.asarray(idx[0]), [1, 0, 0])
        np.testing.assert_allclose(np.asarray(bary[0]), [1.0, 0, 0])
        np.testing.assert_allclose(np.asarray(bary[1]), [0.2, 0.5, 0.3])

    def test_landmark_loss_zero_at_truth(self):
        import jax.numpy as jnp
        import numpy as np

        from mesh_tpu.models import lbs
        from mesh_tpu.parallel import landmark_arrays, landmark_loss

        model = self._tiny_model()
        betas = jnp.zeros((1, model.num_betas))
        pose = jnp.zeros((1, model.num_joints, 3))
        verts, _ = lbs(model, betas, pose)
        regs = {
            "a": (np.array([0, 1, 2]), np.array([0.3, 0.3, 0.4])),
            "b": (np.array([10]), np.array([1.0])),
        }
        idx, bary, names = landmark_arrays(regs)
        ring = np.asarray(verts)[0][np.asarray(idx)]
        target = (ring * np.asarray(bary)[..., None]).sum(1)[None]
        loss = landmark_loss(verts, idx, bary, jnp.asarray(target))
        assert float(loss) < 1e-10

    def test_landmarks_pull_fit_toward_targets(self):
        import jax.numpy as jnp
        import numpy as np

        from mesh_tpu.models import lbs
        from mesh_tpu.parallel import (
            init_fit_state,
            landmark_arrays,
            make_fit_step,
            scan_to_model_loss,
        )

        model = self._tiny_model()
        rng = np.random.RandomState(1)
        true_betas = jnp.asarray(rng.randn(1, model.num_betas) * 0.5, jnp.float32)
        true_pose = jnp.zeros((1, model.num_joints, 3))
        target_verts, _ = lbs(model, true_betas, true_pose)
        scan = target_verts[:, ::3]  # sparse "scan" of the target surface

        regs = {"l%d" % i: (np.array([i * 7]), np.array([1.0])) for i in range(5)}
        idx, bary, names = landmark_arrays(regs)
        lm_target = jnp.asarray(np.asarray(target_verts)[:, [i * 7 for i in range(5)]])

        state, optimizer = init_fit_state(model, 1)
        step = make_fit_step(
            model, optimizer, landmarks=(idx, bary, lm_target),
            landmark_weight=10.0,
        )
        loss0 = None
        for i in range(60):
            state, loss = step(state, scan)
            loss0 = loss0 if loss0 is not None else float(loss)
        assert float(loss) < loss0 * 0.5  # optimization makes real progress
        # fitted landmarks end up near their targets
        verts, _ = lbs(model, state.betas, state.pose, state.trans)
        got = np.asarray(verts)[0][[i * 7 for i in range(5)]]
        err = np.linalg.norm(got - np.asarray(lm_target)[0], axis=1)
        assert err.max() < 0.15


@slow_on_cpu_sim
@needs_devices
class TestShardedVisibility:
    def test_matches_single_device(self):
        import numpy as np

        from mesh_tpu.geometry import vert_normals
        from mesh_tpu.parallel import make_device_mesh, sharded_visibility
        from mesh_tpu.query import visibility_compute
        from .fixtures import icosphere

        v, f = icosphere(2)
        n = np.asarray(vert_normals(v.astype(np.float32), f.astype(np.int32)))
        cams = np.array([[0, 0, 3.0], [3.0, 0, 0]])
        mesh = make_device_mesh(8)
        vis_s, ndc_s = sharded_visibility(v, f, cams, n=n, mesh=mesh)
        vis_1, ndc_1 = visibility_compute(v, f, cams, n=n)
        np.testing.assert_array_equal(vis_s, vis_1)
        np.testing.assert_allclose(ndc_s, ndc_1, atol=1e-6)

    def test_non_divisible_vertex_count(self):
        import numpy as np

        from mesh_tpu.parallel import make_device_mesh, sharded_visibility
        from mesh_tpu.query import visibility_compute
        from .fixtures import icosphere

        v, f = icosphere(1)  # 42 verts, not divisible by 8
        cams = np.array([[0, 0, 3.0]])
        mesh = make_device_mesh(8)
        vis_s, _ = sharded_visibility(v, f, cams, mesh=mesh)
        vis_1, _ = visibility_compute(v, f, cams)
        assert vis_s.shape == vis_1.shape == (1, 42)
        np.testing.assert_array_equal(vis_s, vis_1)


class TestCheckpoint:
    """Fit-state checkpoint/resume via orbax (SURVEY.md section 5: the
    reference's nearest analog is the topology disk cache)."""

    @pytest.mark.parametrize(
        "use_mesh",
        [False, pytest.param(True, marks=[needs_devices, slow_on_cpu_sim])],
        ids=["single_device", "sharded_mesh"],
    )
    def test_save_restore_resumes_bit_identically(self, tmp_path, use_mesh):
        """Checkpoint -> restore -> one more step equals the uninterrupted
        run, bit for bit.  The sharded variant also regresses the mixed
        committed-placement bug: opt_state scalars used to land committed on
        device 0 while params spanned the mesh, making jit reject the
        restored state."""
        import numpy as np

        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.parallel import (
            init_fit_state,
            make_fit_step,
            restore_fit_state,
            save_fit_state,
        )
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(1)
        model = synthetic_body_model(
            seed=0, n_betas=3, n_joints=4, template=(v, f.astype(np.int32))
        )
        if use_mesh:
            mesh = make_device_mesh(8, ("dp", "sp"), shape=(4, 2))
            batch = 8
        else:
            mesh = None
            batch = 2
        state, optimizer = init_fit_state(model, batch)
        step = make_fit_step(model, optimizer, mesh=mesh)
        rng = np.random.RandomState(0)
        target = rng.randn(batch, 20, 3).astype(np.float32) * 0.5
        for _ in range(3):
            state, loss = step(state, target)

        path = str(tmp_path / "ckpt")
        save_fit_state(path, state, step=3)
        template, _ = init_fit_state(model, batch)
        restored, at_step = restore_fit_state(path, template)
        assert at_step == 3
        np.testing.assert_allclose(
            np.asarray(restored.betas), np.asarray(state.betas), atol=0
        )
        np.testing.assert_allclose(
            np.asarray(restored.pose), np.asarray(state.pose), atol=0
        )

        # resumed optimization continues bit-for-bit: one more step from the
        # restored state equals one more step from the live state
        live_next, live_loss = step(state, target)
        rest_next, rest_loss = step(restored, target)
        np.testing.assert_allclose(
            np.asarray(rest_next.betas), np.asarray(live_next.betas), atol=0
        )
        assert float(rest_loss) == float(live_loss)


# distributed bootstrap helpers are covered in tests/test_distributed.py
