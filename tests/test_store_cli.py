"""``mesh-tpu store``: the jax-free corpus CLI and its rc contract.

rc 0 = healthy, rc 1 = corruption found, rc 2 = unreadable store or
arguments — pinned in subprocesses, exactly as operators and cron jobs
consume it.  The commands must work on hosts with no accelerator
stack, so every child runs without a jax backend init.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mesh_tpu.store import MeshStore

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _store_cli(root, *argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    return subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "store", "--root",
         str(root)] + list(argv),
        capture_output=True, text=True, env=env, cwd=_REPO, timeout=120)


def _soup(seed=0, n_v=150, n_f=320):
    rng = np.random.default_rng(seed)
    v = rng.normal(size=(n_v, 3)).astype(np.float32)
    f = rng.integers(0, n_v, size=(n_f, 3)).astype(np.int32)
    return v, f


@pytest.fixture()
def corpus(tmp_path):
    """A store with two healthy objects; returns (root, [digests])."""
    root = str(tmp_path / "store")
    store = MeshStore(root)
    digests = [store.ingest(*_soup(i)) for i in range(2)]
    return root, digests, store


class TestHealthyRc0:

    def test_ls_lists_objects(self, corpus):
        root, digests, _ = corpus
        res = _store_cli(root, "ls")
        assert res.returncode == 0, res.stderr
        for d in digests:
            assert d in res.stdout

    def test_ls_json_round_trips(self, corpus):
        root, digests, _ = corpus
        res = _store_cli(root, "ls", "--json")
        assert res.returncode == 0, res.stderr
        doc = json.loads(res.stdout)
        assert sorted(o["digest"] for o in doc["objects"]) == \
            sorted(digests)

    def test_ls_empty_store(self, tmp_path):
        res = _store_cli(tmp_path / "fresh", "ls")
        assert res.returncode == 0, res.stderr
        assert "no objects" in res.stdout

    def test_stat_prints_schema_fields(self, corpus):
        root, digests, _ = corpus
        res = _store_cli(root, "stat", digests[0], "--json")
        assert res.returncode == 0, res.stderr
        doc = json.loads(res.stdout)
        assert doc["digest"] == digests[0]
        assert "exact" in doc["tiers"] and "compact" in doc["tiers"]

    def test_verify_clean(self, corpus):
        root, _, _ = corpus
        res = _store_cli(root, "verify")
        assert res.returncode == 0, res.stderr
        assert "OK" in res.stdout

    def test_gc_dry_run_and_real(self, corpus):
        root, digests, store = corpus
        res = _store_cli(root, "gc", "--budget-mb", "0", "--dry-run",
                         "--json")
        assert res.returncode == 0, res.stderr
        assert json.loads(res.stdout)["deleted"] == digests
        assert sorted(store.ls()) == sorted(digests)    # nothing deleted
        res = _store_cli(root, "gc", "--budget-mb", "0")
        assert res.returncode == 0, res.stderr
        assert store.ls() == []


class TestCorruptionRc1:

    def test_verify_bitflip_rc1_names_object(self, corpus):
        root, digests, store = corpus
        man = store.manifest(digests[0])
        path = os.path.join(store.object_dir(digests[0]),
                            man["tiers"]["exact"]["v"][0]["file"])
        data = bytearray(open(path, "rb").read())
        data[-1] ^= 0xFF
        open(path, "wb").write(bytes(data))
        res = _store_cli(root, "verify")
        assert res.returncode == 1
        assert "CORRUPT" in res.stdout
        assert digests[0] in res.stdout
        # the other object still verifies clean on its own
        res = _store_cli(root, "verify", digests[1])
        assert res.returncode == 0, res.stderr

    def test_stat_manifest_drift_rc1(self, corpus):
        root, digests, store = corpus
        man_path = store.manifest_path(digests[0])
        doc = json.load(open(man_path))
        doc["digest"] = "deadbeef-deadbeef-v9-f9"
        json.dump(doc, open(man_path, "w"))
        res = _store_cli(root, "stat", digests[0])
        assert res.returncode == 1
        assert "CORRUPT" in res.stderr


class TestUnreadableRc2:

    def test_stat_unknown_digest_rc2(self, corpus):
        root, _, _ = corpus
        res = _store_cli(root, "stat", "0badc0de-0badc0de-v3-f1")
        assert res.returncode == 2
        assert "store:" in res.stderr

    def test_root_is_a_file_rc2(self, tmp_path):
        bogus = tmp_path / "not_a_dir"
        bogus.write_text("hello")
        res = _store_cli(bogus, "ls")
        assert res.returncode == 2

    def test_verify_unknown_digest_rc2(self, corpus):
        root, _, _ = corpus
        res = _store_cli(root, "verify", "0badc0de-0badc0de-v3-f1")
        assert res.returncode == 2
