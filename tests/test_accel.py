"""mesh_tpu.accel: index correctness, certificates, cache, and routing.

The load-bearing claims under test (ISSUE 7 acceptance):

- BVH and grid traversals are bit-identical to the dense brute reference
  on random AND degenerate (sliver / duplicate / zero-area) meshes —
  directly on tight queries, via the certificate/fallback facade
  everywhere.
- Certificates are conservative: there is no tight-but-wrong query.
- A topology-digest cache hit skips the host build entirely.
- The accel path's exact pair tests are sub-linear in F.
- auto routes to accel above the crossover and records the chosen
  strategy exactly once per call.
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp                                   # noqa: E402

from mesh_tpu.accel import build as accel_build           # noqa: E402
from mesh_tpu.accel.build import (                        # noqa: E402
    AccelIndex,
    build_bvh,
    build_grid,
    clear_index_cache,
    get_index,
    index_cache_info,
    topology_digest,
)
from mesh_tpu.accel.traverse import (                     # noqa: E402
    bvh_closest_point,
    bvh_search_faces,
    closest_faces_and_points_accel,
    grid_closest_point,
)
from mesh_tpu.query.autotune import _sphere_mesh          # noqa: E402
from mesh_tpu.query.closest_point import (                # noqa: E402
    closest_faces_and_points,
)


def _dense(v, f, q):
    res = closest_faces_and_points(jnp.asarray(v), jnp.asarray(f),
                                   jnp.asarray(q))
    return {k: np.asarray(val) for k, val in res.items()}


def _random_soup(seed, n_v=200, n_f=600, n_q=150, spread=1.0, shift=0.0):
    rng = np.random.default_rng(seed)
    v = (rng.normal(size=(n_v, 3)) * spread + shift).astype(np.float32)
    f = rng.integers(0, n_v, size=(n_f, 3)).astype(np.int32)
    q = (rng.normal(size=(n_q, 3)) * spread * 1.5 + shift).astype(
        np.float32)
    return v, f, q


def _degenerate_mesh(n_q=120):
    """Slivers, duplicated faces, zero-area (repeated-vertex) faces, and
    exact duplicate geometry — every class the safe tile exists for."""
    rng = np.random.default_rng(7)
    v = rng.normal(size=(60, 3)).astype(np.float32)
    # slivers: two nearly colinear edges
    v[10] = v[9] + np.float32(1e-7)
    faces = [rng.integers(0, 60, size=3) for _ in range(80)]
    faces += [[9, 10, k] for k in range(5)]          # sliver family
    faces += [[3, 3, 17], [5, 5, 5]]                 # zero-area
    faces += [[1, 2, 4], [1, 2, 4], [1, 2, 4]]       # duplicates (ties)
    f = np.asarray(faces, np.int32)
    q = rng.normal(size=(n_q, 3)).astype(np.float32)
    return v, f, q


# ---------------------------------------------------------------------------
# bit-identity + conservative certificates


@pytest.mark.parametrize("kind", ["bvh", "grid"])
@pytest.mark.parametrize("seed,shift", [(0, 0.0), (1, 0.0), (2, 50.0)])
def test_tight_queries_bit_identical_random(kind, seed, shift):
    v, f, q = _random_soup(seed, shift=shift)
    ref = _dense(v, f, q)
    fn = bvh_closest_point if kind == "bvh" else grid_closest_point
    out = fn(v, f, q)
    tight = np.asarray(out["tight"])
    # conservative certificate: every tight query matches dense exactly
    for key in ("face", "part", "sqdist"):
        assert np.array_equal(np.asarray(out[key])[tight], ref[key][tight]), \
            "%s: tight-but-wrong %s" % (kind, key)
    assert np.array_equal(np.asarray(out["point"])[tight],
                          ref["point"][tight])


@pytest.mark.parametrize("kind", ["bvh", "grid"])
def test_facade_bit_identical_degenerate(kind):
    v, f, q = _degenerate_mesh()
    ref = _dense(v, f, q)
    out = closest_faces_and_points_accel(v, f, q, kind=kind)
    for key in ("face", "part", "sqdist", "point"):
        assert np.array_equal(out[key], ref[key]), \
            "%s facade diverges from dense on %s" % (kind, key)


@pytest.mark.parametrize("kind", ["bvh", "grid"])
def test_facade_bit_identical_random(kind):
    v, f, q = _random_soup(3, n_f=900, n_q=250)
    ref = _dense(v, f, q)
    out, stats = closest_faces_and_points_accel(
        v, f, q, kind=kind, with_stats=True)
    for key in ("face", "part", "sqdist", "point"):
        assert np.array_equal(out[key], ref[key])
    assert stats["kind"] == kind
    assert stats["backend"] == "xla"          # CPU test platform
    assert stats["pair_tests"] > 0


def test_sublinear_pair_tests_on_structured_mesh():
    v, f = _sphere_mesh(20000)
    rng = np.random.default_rng(4)
    cent = np.asarray(v, np.float32)[np.asarray(f)].mean(1)
    q = (cent[rng.integers(0, len(f), 256)]
         + rng.normal(scale=0.03, size=(256, 3))).astype(np.float32)
    out = bvh_closest_point(v, f, q)
    mean_pairs = float(np.asarray(out["pair_tests"]).mean())
    assert mean_pairs < 0.2 * f.shape[0], \
        "BVH pair tests %.0f not sub-linear vs F=%d" % (
            mean_pairs, f.shape[0])
    assert bool(np.asarray(out["tight"]).all())


# ---------------------------------------------------------------------------
# index construction + digest cache


def test_accel_index_frozen_and_pytree():
    v, f, _ = _random_soup(5)
    idx = build_bvh(v, f)
    with pytest.raises(AttributeError):
        idx.kind = "grid"
    leaves, treedef = jax.tree_util.tree_flatten(idx)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(rebuilt, AccelIndex)
    assert rebuilt.kind == idx.kind and rebuilt.digest == idx.digest
    assert sorted(rebuilt.arrays) == sorted(idx.arrays)


def test_topology_digest_tracks_content():
    v, f, _ = _random_soup(6)
    d0 = topology_digest(v, f)
    assert d0 == topology_digest(v.copy(), f.copy())
    v2 = v.copy()
    v2[0, 0] += np.float32(1e-3)
    assert topology_digest(v2, f) != d0
    f2 = f.copy()
    f2[0, 0] = (f2[0, 0] + 1) % v.shape[0]
    assert topology_digest(v, f2) != d0


def test_digest_cache_hit_skips_host_build():
    v, f, _ = _random_soup(8)
    clear_index_cache()
    idx1 = get_index(v, f, kind="bvh")
    assert index_cache_info()["entries"] == 1

    def boom(*a, **k):
        raise AssertionError("cache hit must not rebuild")

    orig = accel_build._BUILDERS["bvh"]
    accel_build._BUILDERS["bvh"] = boom
    try:
        idx2 = get_index(v, f, kind="bvh")
    finally:
        accel_build._BUILDERS["bvh"] = orig
    assert idx2 is idx1
    from mesh_tpu.obs.metrics import REGISTRY

    hits = REGISTRY.get("mesh_tpu_accel_cache_hits_total")
    assert hits is not None and hits.value(kind="bvh") >= 1


def test_cache_bounded():
    clear_index_cache()
    for seed in range(accel_build._MAX_CACHED + 3):
        v, f, _ = _random_soup(seed, n_v=40, n_f=60)
        get_index(v, f, kind="bvh")
    assert index_cache_info()["entries"] == accel_build._MAX_CACHED
    clear_index_cache()
    assert index_cache_info()["entries"] == 0


def test_grid_index_shapes_consistent():
    v, f, _ = _random_soup(9)
    idx = build_grid(v, f)
    res, cap = idx.meta["res"], idx.meta["cap"]
    assert idx.arrays["cell_table"].shape == (res ** 3, cap)
    assert idx.arrays["cell_start"].shape == (res ** 3 + 1,)
    # CSR covers each face at least once (conservative AABB binning)
    assert set(np.unique(np.asarray(idx.arrays["cell_faces"]))) >= set(
        range(f.shape[0]))


# ---------------------------------------------------------------------------
# routing: auto strategy, metric once-per-call, env hatches


def _strategy_counter():
    from mesh_tpu.obs.metrics import REGISTRY

    return REGISTRY.counter(
        "mesh_tpu_query_strategy_total",
        "closest_faces_and_points_auto kernel-path decisions.")


def test_auto_routes_to_accel_above_crossover(monkeypatch):
    from mesh_tpu.query.culled import closest_faces_and_points_auto

    monkeypatch.setenv("MESH_TPU_ACCEL_MIN_FACES", "500")
    v, f, q = _random_soup(10, n_f=800)
    counter = _strategy_counter()
    before = counter.value(path="accel_bvh")
    out = closest_faces_and_points_auto(v, f, q)
    assert counter.value(path="accel_bvh") == before + 1
    ref = _dense(v, f, q)
    for key in ("face", "sqdist"):
        assert np.array_equal(out[key], ref[key])


def test_auto_strategy_recorded_once_even_with_fallback(monkeypatch):
    """The satellite fix: one auto call == one strategy increment, no
    matter how many loose-certificate queries re-run through brute."""
    from mesh_tpu.query.culled import closest_faces_and_points_auto

    monkeypatch.setenv("MESH_TPU_NO_ACCEL", "1")
    monkeypatch.setenv("MESH_TPU_BRUTE_MAX_FACES", "100")
    # far-field soup: culled certificates miss often -> fallback fires
    v, f, q = _random_soup(11, n_f=400, spread=0.3)
    counter = _strategy_counter()
    before_total = counter.total()
    before_culled = counter.value(path="xla_culled")
    closest_faces_and_points_auto(v, f, q)
    assert counter.total() == before_total + 1
    assert counter.value(path="xla_culled") == before_culled + 1


def test_auto_accel_grid_label(monkeypatch):
    from mesh_tpu.query.culled import closest_faces_and_points_auto

    monkeypatch.setenv("MESH_TPU_ACCEL_MIN_FACES", "500")
    monkeypatch.setenv("MESH_TPU_ACCEL_KIND", "grid")
    v, f, q = _random_soup(12, n_f=700)
    counter = _strategy_counter()
    before = counter.value(path="accel_grid")
    out = closest_faces_and_points_auto(v, f, q)
    assert counter.value(path="accel_grid") == before + 1
    assert np.array_equal(out["sqdist"], _dense(v, f, q)["sqdist"])


def test_no_accel_kill_switch(monkeypatch):
    from mesh_tpu.query.culled import closest_faces_and_points_auto

    monkeypatch.setenv("MESH_TPU_ACCEL_MIN_FACES", "500")
    monkeypatch.setenv("MESH_TPU_NO_ACCEL", "1")
    v, f, q = _random_soup(13, n_f=700)
    counter = _strategy_counter()
    before = counter.value(path="accel_bvh")
    closest_faces_and_points_auto(v, f, q)
    assert counter.value(path="accel_bvh") == before


def test_accel_crossover_env_and_default(monkeypatch):
    from mesh_tpu.query import autotune

    monkeypatch.setenv("MESH_TPU_ACCEL_MIN_FACES", "4242")
    assert autotune.accel_crossover_faces() == 4242
    monkeypatch.setenv("MESH_TPU_ACCEL_MIN_FACES", "junk")
    monkeypatch.setattr(autotune, "_accel_measured", None)
    monkeypatch.setattr(autotune, "_accel_cache_path",
                        lambda: "/nonexistent/nope.json")
    assert (autotune.accel_crossover_faces()
            == autotune.ACCEL_DEFAULT_CROSSOVER)


# ---------------------------------------------------------------------------
# engine / diff / serve integration


def test_engine_companion_is_cached_index():
    from mesh_tpu.engine.planner import get_planner

    v, f, _ = _random_soup(14)
    clear_index_cache()
    idx = get_planner().accel_companion(v, f, kind="bvh")
    assert isinstance(idx, AccelIndex)
    assert get_planner().accel_companion(v, f, kind="bvh") is idx


def test_diff_accel_index_matches_dense_path():
    from mesh_tpu.diff.queries import closest_point as diff_cp

    v, f, q = _random_soup(15, n_f=500)
    idx = get_index(v, f, kind="bvh")
    ref = diff_cp(jnp.asarray(v), jnp.asarray(f), jnp.asarray(q))
    out = diff_cp(jnp.asarray(v), jnp.asarray(f), jnp.asarray(q),
                  accel_index=idx)
    assert np.array_equal(np.asarray(out["face"]), np.asarray(ref["face"]))

    def loss(vv, use_idx):
        r = diff_cp(vv, jnp.asarray(f), jnp.asarray(q),
                    accel_index=idx if use_idx else None)
        return jnp.sum(r["sqdist"])

    g_ref = jax.grad(lambda vv: loss(vv, False))(jnp.asarray(v))
    g_acc = jax.grad(lambda vv: loss(vv, True))(jnp.asarray(v))
    np.testing.assert_array_equal(np.asarray(g_ref), np.asarray(g_acc))


def test_bvh_search_faces_rejects_grid():
    v, f, q = _random_soup(16)
    idx = get_index(v, f, kind="grid")
    with pytest.raises(ValueError, match="bvh"):
        bvh_search_faces(idx, jnp.asarray(v), jnp.asarray(f),
                         jnp.asarray(q))


def test_serve_accel_rung(monkeypatch):
    from mesh_tpu.serve.deadline import (
        Deadline,
        default_ladder,
        run_with_ladder,
    )

    monkeypatch.setenv("MESH_TPU_SERVE_LADDER", "accel,anchored")
    ladder = default_ladder()
    assert [r.name for r in ladder] == ["accel", "anchored"]

    class M(object):
        pass

    mesh = M()
    mesh.v, mesh.f = _sphere_mesh(3000)
    rng = np.random.default_rng(17)
    pts = rng.normal(size=(40, 3))
    res, retries = run_with_ladder(mesh, pts, Deadline(10.0), ladder=ladder)
    assert res.rung == "accel"
    assert res.certified       # exact-by-fallback: always certified
    assert res.faces.shape == (1, 40)


def test_default_ladder_unchanged_without_env(monkeypatch):
    from mesh_tpu.serve.deadline import default_ladder

    monkeypatch.delenv("MESH_TPU_SERVE_LADDER", raising=False)
    assert [r.name for r in default_ladder()] == [
        "engine", "culled", "anchored"]


# ---------------------------------------------------------------------------
# Pallas rope kernel (interpret mode — chip-free)


def test_pallas_bvh_interpret_matches_dense():
    from mesh_tpu.accel.pallas_bvh import closest_point_pallas_bvh

    v, f = _sphere_mesh(4000)
    v = np.asarray(v, np.float32)
    f = np.asarray(f, np.int32)
    rng = np.random.default_rng(18)
    cent = v[f].mean(1)
    q = (cent[rng.integers(0, len(f), 200)]
         + rng.normal(scale=0.05, size=(200, 3))).astype(np.float32)
    ref = _dense(v, f, q)
    out = closest_point_pallas_bvh(v, f, q, tile_q=64, tile_f=256,
                                   interpret=True)
    sq = np.asarray(out["sqdist"])
    np.testing.assert_allclose(sq, ref["sqdist"], rtol=1e-5, atol=1e-7)
    # exact up to distance ties: any face disagreement must be a tie
    diff = np.asarray(out["face"]) != ref["face"]
    assert np.allclose(sq[diff], ref["sqdist"][diff], rtol=1e-5, atol=1e-7)
    assert bool(np.asarray(out["tight"]).all())
    assert np.asarray(out["pair_tests"]).min() >= 0


def test_pallas_bvh_rejects_mismatched_leaf_size():
    from mesh_tpu.accel.pallas_bvh import closest_point_pallas_bvh

    v, f, q = _random_soup(19)
    idx = build_bvh(v, f, leaf_size=8)
    with pytest.raises(ValueError, match="leaf_size"):
        closest_point_pallas_bvh(v, f, q, tile_f=256, interpret=True,
                                 index=idx)


# ---------------------------------------------------------------------------
# perfcheck accel bands (stdlib-only surface)


def _accel_rec(value=0.98, checksum=123.4567, ppq=4000.0, faces=200000):
    return {"metric": "accel_proxy_skip_ratio", "value": value,
            "unit": "pair_tests_skipped_frac", "checksum": checksum,
            "pair_tests_per_query": ppq, "faces": faces}


def test_perfcheck_accel_band_pass_and_fail():
    from mesh_tpu.obs.perf import perfcheck

    golden = _accel_rec()
    doc = {"metric": "x", "value": None, "unit": None,
           "accel": _accel_rec()}
    rc, lines = perfcheck(doc, accel_golden=golden)
    assert rc == 0
    assert any("ok accel pair-tests-skipped" in ln for ln in lines)

    doc_bad = {"metric": "x", "value": None, "unit": None,
               "accel": _accel_rec(value=0.5)}
    rc, lines = perfcheck(doc_bad, accel_golden=golden)
    assert rc == 1
    assert any(ln.startswith("FAIL accel pair-tests-skipped")
               for ln in lines)


def test_perfcheck_accel_checksum_drift_fails():
    from mesh_tpu.obs.perf import perfcheck

    golden = _accel_rec()
    doc = {"metric": "x", "value": None, "unit": None,
           "accel": _accel_rec(checksum=123.5)}
    rc, lines = perfcheck(doc, accel_golden=golden)
    assert rc == 1
    assert any("FAIL accel checksum" in ln for ln in lines)


def test_perfcheck_missing_accel_with_golden_fails():
    from mesh_tpu.obs.perf import perfcheck

    rc, lines = perfcheck({"metric": "x", "value": None, "unit": None},
                          accel_golden=_accel_rec())
    assert rc == 1
    assert any("FAIL accel" in ln for ln in lines)


def test_extract_records_accel_slots():
    from mesh_tpu.obs.perf import extract_records

    partial = {"kind": "bench_partial", "stages": {
        "accel_proxy": {"status": "ok", "record": _accel_rec()}}}
    assert extract_records(partial)["accel"]["value"] == 0.98
    final = {"metric": "x", "value": 1.0, "accel": _accel_rec(value=0.95)}
    assert extract_records(final)["accel"]["value"] == 0.95


def test_committed_accel_golden_meets_acceptance():
    """The committed golden IS the acceptance evidence: >=200k faces,
    skip ratio >= 0.9, every certificate tight."""
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        "accel_golden.json")
    with open(path) as fh:
        rec = json.load(fh)
    assert rec["faces"] >= 200000
    assert rec["value"] >= 0.9
    assert rec["tight_frac"] == 1.0
    assert rec["pair_tests_per_query"] < rec["faces"]


# ---------------------------------------------------------------------------
# scale (tier-2)


@pytest.mark.slow
def test_million_face_build_and_traverse():
    v, f = _sphere_mesh(1_000_000)
    idx = build_bvh(v, f)
    assert idx.meta["n_faces"] == f.shape[0] >= 990_000
    rng = np.random.default_rng(20)
    q = rng.normal(size=(128, 3)).astype(np.float32)
    out = bvh_closest_point(v, f, q, index=idx)
    assert bool(np.asarray(out["tight"]).all())
    ref = _dense(v, f, q)
    assert np.array_equal(np.asarray(out["sqdist"]), ref["sqdist"])
    assert float(np.asarray(out["pair_tests"]).mean()) < 0.05 * f.shape[0]
