"""Möller no-div triangle-triangle variant: decision parity with the
segment formulation (the semantic oracle) wherever the decision is robust,
shared-arithmetic parity between the XLA and Pallas paths, and the
degeneracy gate that keeps the blind spot out of production.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mesh_tpu.query.ray import (
    _tri_tri_algorithm,
    tri_tri_intersects,
    tri_tri_intersects_moller,
)
from mesh_tpu.query.pallas_ray import tri_tri_any_hit_pallas
from mesh_tpu.utils.jax_compat import enable_x64


def _pair(p, q):
    p = jnp.asarray(np.asarray(p, np.float64))[None]
    q = jnp.asarray(np.asarray(q, np.float64))[None]
    seg = bool(np.asarray(tri_tri_intersects(p, q))[0])
    mol = bool(np.asarray(tri_tri_intersects_moller(p, q))[0])
    return seg, mol


CASES = [
    # crossing: edge of one pierces the face of the other
    ([[0, 0, 0], [2, 0, 0], [0, 2, 0]],
     [[0.5, 0.5, -1], [0.5, 0.5, 1], [2.5, 2.5, 0.5]], True),
    # clearly separated, parallel planes
    ([[0, 0, 0], [1, 0, 0], [0, 1, 0]],
     [[0, 0, 1], [1, 0, 1], [0, 1, 1]], False),
    # separated in-plane direction, same plane band
    ([[0, 0, 0], [1, 0, 0], [0, 1, 0]],
     [[5, 5, -0.5], [6, 5, 0.5], [5, 6, 0.2]], False),
    # perpendicular, T-configuration (edge hits interior)
    ([[0, 0, 0], [2, 0, 0], [0, 2, 0]],
     [[0.3, 0.3, -0.5], [0.3, 0.3, 0.5], [1.5, 0.3, 0.1]], True),
    # star / mutual piercing
    ([[-1, 0, 0], [1, 0, 0], [0, 0, 1.5]],
     [[0, -1, 0.5], [0, 1, 0.5], [0, 0, -1]], True),
    # near miss above the plane
    ([[0, 0, 0], [2, 0, 0], [0, 2, 0]],
     [[0.5, 0.5, 0.2], [1.5, 0.5, 1.0], [0.5, 1.5, 1.0]], False),
    # coplanar overlapping: BOTH forms report no intersection (module
    # docstring: coplanar pairs are not counted; generic float data never
    # produces them)
    ([[0, 0, 0], [2, 0, 0], [0, 2, 0]],
     [[0.5, 0.5, 0], [1.5, 0.5, 0], [0.5, 1.5, 0]], False),
]


@pytest.mark.parametrize("p,q,expect", CASES)
def test_structured_cases(p, q, expect):
    seg, mol = _pair(p, q)
    assert seg == expect, "segment oracle disagrees with the construction"
    assert mol == expect, "moller disagrees with the construction"


def test_symmetry():
    for p, q, expect in CASES:
        seg, mol = _pair(q, p)
        assert mol == expect and seg == expect


def test_random_battery_matches_segment_oracle_where_robust():
    # 4000 random pairs at mixed scales; oracle = GENUINE f64 segment test
    # (enable_x64 — without it jnp silently downcasts to f32, test_pallas
    # guards the same pitfall).  A pair counts as ROBUST when the f64
    # oracle's decision survives five 1e-6-scale jitters of every vertex —
    # borderline grazing contact is exactly where eps conventions may
    # differ, and is excluded from the parity claim (both answers are
    # defensible there).
    import jax

    rng = np.random.RandomState(0)
    n = 4000
    p = rng.randn(n, 3, 3)
    q = rng.randn(n, 3, 3) * rng.choice([0.3, 1.0, 3.0], (n, 1, 1))
    q[:, :, 2] *= rng.choice([0.05, 1.0], (n, 1))   # some near-planar pairs

    with enable_x64(True):
        pj = jnp.asarray(p)
        qj = jnp.asarray(q)
        assert pj.dtype == jnp.float64
        oracle = np.asarray(tri_tri_intersects(pj, qj))
        robust = np.ones(n, bool)
        for k in range(5):
            jit_rng = np.random.RandomState(100 + k)
            pj2 = jnp.asarray(p + jit_rng.randn(*p.shape) * 1e-6)
            qj2 = jnp.asarray(q + jit_rng.randn(*q.shape) * 1e-6)
            robust &= np.asarray(tri_tri_intersects(pj2, qj2)) == oracle
        assert robust.mean() > 0.97, "jitter filter removed too many pairs"

        moller64 = np.asarray(tri_tri_intersects_moller(pj, qj))
    mism64 = np.nonzero((moller64 != oracle) & robust)[0]
    assert mism64.size == 0, (
        "f64 moller disagrees with robust f64 segment oracle at %s"
        % mism64[:10])

    moller32 = np.asarray(tri_tri_intersects_moller(
        jnp.asarray(p, jnp.float32), jnp.asarray(q, jnp.float32)))
    mism32 = np.nonzero((moller32 != oracle) & robust)[0]
    assert mism32.size == 0, (
        "f32 moller disagrees with robust f64 segment oracle at %s"
        % mism32[:10])


def test_large_coordinate_extents_no_overflow():
    # the no-div interval terms scale as extent^13; before the joint
    # unit-box prescale (pallas_ray.moller_prescale), mm-scale coordinates
    # (extent ~2e3) overflowed f32 to inf/NaN and NaN interval endpoints
    # reported overlap — spurious intersections for plane-straddling but
    # disjoint pairs (advisor round-4).  Every CASES decision must hold
    # verbatim at extent ~2e3 and with a far-from-origin offset in f32.
    for scale, offset in ((2e3, 0.0), (1.0, 1e4), (2e3, 5e4)):
        for p, q, expect in CASES:
            pf = (np.asarray(p, np.float32) * scale + offset)
            qf = (np.asarray(q, np.float32) * scale + offset)
            mol = bool(np.asarray(tri_tri_intersects_moller(
                jnp.asarray(pf)[None], jnp.asarray(qf)[None]))[0])
            assert mol == expect, (
                "moller decision changed at scale %g offset %g" % (
                    scale, offset))


def test_random_battery_at_mm_scale_matches_unit_scale():
    # scaling is a similarity transform: every decision at extent ~1 must
    # survive a uniform x2000 (and offset) in f32 — the regime the advisor
    # flagged.  Uses moller-vs-moller (not the segment oracle) so the only
    # variable is the coordinate scale.
    rng = np.random.RandomState(7)
    n = 2000
    p = rng.randn(n, 3, 3).astype(np.float32)
    q = (rng.randn(n, 3, 3) * rng.choice([0.3, 1.0, 3.0], (n, 1, 1))
         ).astype(np.float32)
    base = np.asarray(tri_tri_intersects_moller(
        jnp.asarray(p), jnp.asarray(q)))
    scaled = np.asarray(tri_tri_intersects_moller(
        jnp.asarray(p * 2000.0 + 1e4), jnp.asarray(q * 2000.0 + 1e4)))
    # f32 rounding of (x * 2000 + 1e4) itself perturbs vertices by ~1e-3
    # relative, so a few borderline pairs may legitimately flip; overflow
    # flipped ~half of the straddling-disjoint population
    assert (scaled != base).mean() < 0.005, (
        "mm-scale decisions diverged from unit-scale on %d/%d pairs"
        % (int((scaled != base).sum()), n))


def test_heterogeneous_batch_no_scale_coupling():
    # the prescale is shared across the whole batch; with unit plane
    # normals the shared scale shrinks plane distances only LINEARLY, so
    # a unit-scale intersecting pair must keep its decision even when a
    # far-away pair in the same batch blows the joint bbox up to ~1e4
    # (code-review round-5 scenario: cubic scaling clamped the near pair
    # below eps and flipped it to coplanar-reject)
    near_p = np.asarray(CASES[0][0], np.float32)
    near_q = np.asarray(CASES[0][1], np.float32)
    far_p = np.asarray(CASES[1][0], np.float32) + 1e4
    far_q = np.asarray(CASES[1][1], np.float32) + 1e4
    p = jnp.asarray(np.stack([near_p, far_p]))
    q = jnp.asarray(np.stack([near_q, far_q]))
    got = np.asarray(tri_tri_intersects_moller(p, q))
    assert got[0] == CASES[0][2] and got[1] == CASES[1][2]


def test_small_triangles_in_large_scene_not_coplanar_clamped():
    # fine tessellation: unit-ish triangles in a scene of extent ~2e3
    # (mm-scale scan).  After the unit-box prescale the triangles are
    # ~1e-3 of the scene; unit normals keep their plane distances ~1e-3,
    # far above eps=1e-9 — an intersecting pair must still be seen
    cross_p = np.asarray(CASES[0][0], np.float32)          # unit pair,
    cross_q = np.asarray(CASES[0][1], np.float32)          # intersecting
    anchor = np.float32([[1e3, 1e3, 1e3], [1e3 + 1, 1e3, 1e3],
                         [1e3, 1e3 + 1, 1e3]])             # stretches bbox
    p = jnp.asarray(np.stack([cross_p, anchor]))
    q = jnp.asarray(np.stack([cross_q, anchor + np.float32([0, 0, 9])]))
    got = np.asarray(tri_tri_intersects_moller(p, q))
    assert bool(got[0]) is True


def test_outlier_does_not_blind_small_pairs():
    # the degeneracy cut in _tri_planes is RELATIVE (n2 vs |e1|^2|e2|^2),
    # so a unit pair stays live however the joint prescale shrinks it —
    # up to f32's representational floor: past ~1e7 relative scene
    # extent the CENTERING itself quantizes small features away
    # (ulp(offset) exceeds the edges), which no cutoff choice can save
    # (documented in moller_prescale).  Assert the whole supported range.
    near_p = np.asarray(CASES[0][0], np.float32)
    near_q = np.asarray(CASES[0][1], np.float32)
    for off in (1e4, 1e5, 3e6):
        outlier = np.float32([[off, off, off],
                              [off * 1.001, off, off],
                              [off, off * 1.001, off]])
        p = jnp.asarray(np.stack([near_p, outlier]))
        q = jnp.asarray(np.stack(
            [near_q, outlier + np.float32([0, 0, off / 10])]))
        got = np.asarray(tri_tri_intersects_moller(p, q))
        assert bool(got[0]) is True, (
            "unit pair blinded by an outlier at %g" % off)


def test_empty_inputs():
    # empty query/face sets must trace and return empty, not crash in the
    # prescale reduction (code-review round-5 finding)
    empty = jnp.zeros((0, 3, 3), jnp.float32)
    tri = jnp.asarray(np.asarray(CASES[0][0], np.float32))[None]
    assert np.asarray(tri_tri_intersects_moller(empty, empty)).shape == (0,)
    got = np.asarray(tri_tri_any_hit_pallas(
        tri, tri, tile_q=8, tile_f=8, interpret=True, algorithm="moller"))
    assert got.shape == (1,)


def test_pallas_matches_xla_moller_exactly():
    # identical arithmetic graph: the Pallas tile and the XLA path both
    # call _moller_hit, so agreement is exact — including on degenerate
    # triangles (where both are blind by construction)
    rng = np.random.RandomState(3)
    q_tri = rng.randn(137, 3, 3).astype(np.float32)
    m_tri = rng.randn(201, 3, 3).astype(np.float32)
    # inject degenerates on both sides
    q_tri[5, 2] = q_tri[5, 1]
    m_tri[7] = 0.0
    m_tri[11, 2] = (m_tri[11, 0] + m_tri[11, 1]) / 2

    got = np.asarray(tri_tri_any_hit_pallas(
        q_tri, m_tri, tile_q=32, tile_f=64, interpret=True,
        algorithm="moller"))
    ref = np.asarray(jnp.any(tri_tri_intersects_moller(
        jnp.asarray(q_tri)[:, None], jnp.asarray(m_tri)[None]), axis=1))
    np.testing.assert_array_equal(got, ref)


def test_moller_blindness_and_the_gate():
    # a zero-area needle whose edges pierce a face: the segment form sees
    # it, moller is blind — exactly why the facade only selects moller
    # when BOTH meshes pass the nondegeneracy check
    tri = np.array([[[0, 0, 0], [2, 0, 0], [0, 2, 0]]], np.float64)
    needle = np.array(
        [[[0.5, 0.5, -1], [0.5, 0.5, 1], [0.5, 0.5, 3]]], np.float64)
    seg, mol = (
        bool(np.asarray(tri_tri_intersects(jnp.asarray(needle),
                                           jnp.asarray(tri)))[0]),
        bool(np.asarray(tri_tri_intersects_moller(jnp.asarray(needle),
                                                  jnp.asarray(tri)))[0]),
    )
    assert seg is True and mol is False

    v = np.array([[0, 0, 0], [2, 0, 0], [0, 2, 0]], np.float32)
    f = np.array([[0, 1, 2]], np.int32)
    nv = needle[0].astype(np.float32)
    nf = np.array([[0, 1, 2]], np.int32)
    assert _tri_tri_algorithm(v, f, nv, nf) == "segment"
    # clean geometry on both sides -> the fast tile
    hv = (v + np.array([0, 0, 1], np.float32)).astype(np.float32)
    assert _tri_tri_algorithm(v, f, hv, f) == "moller"


def test_config4_geometry_parity():
    # the hand-body benchmark geometry (grazing icosphere vs body sphere):
    # moller and segment must produce the same mask and count
    from mesh_tpu.models import smpl_sized_sphere
    from mesh_tpu.sphere import _icosphere

    body_v, body_f = smpl_sized_sphere()
    hand_v, hand_f = _icosphere(2)
    hand_v = hand_v * 0.2 + np.array([0.9, 0, 0])

    q_tri = jnp.asarray(hand_v.astype(np.float32))[jnp.asarray(
        hand_f.astype(np.int32))]
    m_tri = jnp.asarray(body_v.astype(np.float32))[jnp.asarray(
        body_f.astype(np.int32))]
    seg = np.asarray(jnp.any(tri_tri_intersects(
        q_tri[:, None], m_tri[None]), axis=1))
    mol = np.asarray(jnp.any(tri_tri_intersects_moller(
        q_tri[:, None], m_tri[None]), axis=1))
    np.testing.assert_array_equal(seg, mol)
    assert seg.sum() > 0       # the fixture does graze the surface


def test_user_eps_is_scale_invariant():
    # a caller-supplied eps is in INPUT units (rescaled internally by the
    # unit-box prescale): scaling the geometry AND the eps by the same
    # factor must not change the decision.  Before the rescale fix, eps
    # was applied raw in prescaled coordinates, so its meaning silently
    # changed with scene extent.
    p = np.array([[[0, 0, 0], [2, 0, 0], [0, 2, 0]]], np.float64)
    # pierces p's plane by only 0.02: a tight eps sees the real
    # intersection; a generous plane-thickening eps clamps all the plane
    # distances to zero -> coplanar classification -> not counted
    # (module docstring: neither form counts coplanar pairs)
    q = np.array([[[0.5, 0.5, -0.02], [1.5, 0.5, 0.01],
                   [0.5, 1.5, 0.01]]], np.float64)

    def run(k, eps):
        return bool(np.asarray(tri_tri_intersects_moller(
            jnp.asarray(p * k), jnp.asarray(q * k), eps=eps))[0])

    for k in (1.0, 1e3):
        assert run(k, 1e-9 * k) is True, "tight input-unit eps, k=%g" % k
        assert run(k, 0.1 * k) is False, "loose input-unit eps, k=%g" % k
    # a FIXED eps shrinks relative to a larger scene: 0.1 units of plane
    # thickening is coplanar-clamping at extent ~2 but negligible at
    # extent ~2000.  Pre-fix, eps lived in unit-box coordinates and 0.1
    # clamped at every scale.
    assert run(1e3, 0.1) is True


def test_f64_sliver_is_not_degeneracy_rejected():
    # corner-angle sine ~3e-7: under the old fixed f32-tuned 1e-12
    # relative cut this valid f64 sliver got a zeroed normal (coplanar
    # reject, blind); the dtype-dependent cut keeps it live in f64
    sliver = np.array(
        [[[0, 0, -1], [0, 0, 1], [1, 3e-7, 0]]], np.float64)
    target = np.array(
        [[[-1, -1, 0], [1, -1, 0], [0, 1, 0]]], np.float64)
    seg, mol = _pair(sliver, target)
    assert seg is True
    assert mol is True
