"""Visibility tests: analytic box, sign-of-coordinate checks, sensors,
extra occluders (port of reference tests/test_visibility.py:13-53 style)."""

import numpy as np

from mesh_tpu.query import visibility_compute
from mesh_tpu.geometry import vert_normals
import jax.numpy as jnp

from .fixtures import box


class TestVisibility:
    def _box(self):
        v, f = box(2.0)
        n = np.asarray(vert_normals(jnp.asarray(v, jnp.float32), jnp.asarray(f, jnp.int32)))
        return v, f, n

    def test_axis_camera(self):
        v, f, n = self._box()
        cam = np.array([[0.0, 0.0, 5.0]])
        vis, ndc = visibility_compute(v, f, cam, n=n)
        assert vis.shape == (1, 8)
        # exactly the verts on the +z face are visible
        np.testing.assert_array_equal(vis[0].astype(bool), v[:, 2] > 0)

    def test_each_side(self):
        v, f, n = self._box()
        for axis in range(3):
            for sign in (+1, -1):
                cam = np.zeros((1, 3))
                cam[0, axis] = sign * 10.0
                vis, _ = visibility_compute(v, f, cam, n=n)
                np.testing.assert_array_equal(
                    vis[0].astype(bool), sign * v[:, axis] > 0,
                    err_msg="axis %d sign %d" % (axis, sign),
                )

    def test_multiple_cameras_batched(self):
        v, f, n = self._box()
        cams = np.array([[0, 0, 5.0], [0, 0, -5.0], [5.0, 0, 0]])
        vis, ndc = visibility_compute(v, f, cams, n=n)
        assert vis.shape == (3, 8)
        np.testing.assert_array_equal(vis[0].astype(bool), v[:, 2] > 0)
        np.testing.assert_array_equal(vis[1].astype(bool), v[:, 2] < 0)
        np.testing.assert_array_equal(vis[2].astype(bool), v[:, 0] > 0)

    def test_extra_occluder_blocks(self):
        v, f, n = self._box()
        # big wall between camera and box
        wall_v = np.array([[-10, -10, 2.5], [10, -10, 2.5], [10, 10, 2.5], [-10, 10, 2.5]])
        wall_f = np.array([[0, 1, 2], [0, 2, 3]])
        cam = np.array([[0.0, 0.0, 5.0]])
        vis, _ = visibility_compute(v, f, cam, n=n, extra_v=wall_v, extra_f=wall_f)
        assert not vis.any()

    def test_min_dist_skips_near_occluders(self):
        # reference tests/test_visibility.py:49-53: an occluder nearer to
        # the vertex than min_dist does not block (the ray starts at
        # vert + min_dist * dir, past it)
        v, f, n = self._box()
        wall_v = np.array(
            [[-10, -10, 2.5], [10, -10, 2.5], [10, 10, 2.5], [-10, 10, 2.5]]
        )
        wall_f = np.array([[0, 1, 2], [0, 2, 3]])
        cam = np.array([[0.0, 0.0, 5.0]])
        # wall is 1.5 in front of the +z face: with min_dist=2.0 the rays
        # start beyond it, so the +z face is visible again
        vis, _ = visibility_compute(
            v, f, cam, n=n, extra_v=wall_v, extra_f=wall_f, min_dist=2.0
        )
        np.testing.assert_array_equal(vis[0].astype(bool), v[:, 2] > 0)
        # sanity: with the default epsilon the same wall blocks everything
        vis0, _ = visibility_compute(
            v, f, cam, n=n, extra_v=wall_v, extra_f=wall_f
        )
        assert not vis0.any()

    def test_n_dot_cam(self):
        v, f, n = self._box()
        cam = np.array([[0.0, 0.0, 100.0]])
        _, ndc = visibility_compute(v, f, cam, n=n)
        # camera is far: dir ~ +z; verts on +z face have n . dir > 0
        assert np.all(ndc[0][v[:, 2] > 0] > 0.3)
        assert np.all(ndc[0][v[:, 2] < 0] < 0.0)
