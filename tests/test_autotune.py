"""Measured brute-vs-culled crossover (query/autotune.py) and its wiring
into closest_faces_and_points_auto."""

import json

import numpy as np
import pytest

import mesh_tpu
from mesh_tpu.query import autotune
from mesh_tpu.query.culled import closest_faces_and_points_auto
from mesh_tpu.query.closest_point import closest_faces_and_points


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch, tmp_path):
    monkeypatch.setattr(autotune, "_measured", None)
    monkeypatch.setattr(autotune, "_mxu_measured", None)
    monkeypatch.setattr(mesh_tpu, "mesh_package_cache_folder", str(tmp_path))
    monkeypatch.delenv("MESH_TPU_BRUTE_MAX_FACES", raising=False)
    monkeypatch.delenv("MESH_TPU_MXU_CROSSOVER_FACES", raising=False)
    yield


def test_sphere_mesh_face_count():
    v, f = autotune._sphere_mesh(10_000)
    assert abs(f.shape[0] - 10_000) / 10_000 < 0.2
    assert f.min() >= 0 and f.max() < v.shape[0]


def test_default_without_measurement():
    assert autotune.crossover_faces() == autotune.DEFAULT_CROSSOVER


def test_env_override_wins(monkeypatch):
    monkeypatch.setenv("MESH_TPU_BRUTE_MAX_FACES", "1234")
    assert autotune.crossover_faces() == 1234


def _deterministic_times(sequence):
    """Patchable _time_best returning canned values in call order."""
    it = iter(sequence)

    def fake(fn, reps):
        return next(it)

    return fake


def test_calibrate_persists_and_reloads(monkeypatch):
    # brute 1.0 always; culled loses at ladder[0], wins at ladder[1];
    # stability recheck agrees -> persist.  Crossover = the largest
    # brute-winning F (ladder[0]'s actual face count).
    monkeypatch.setattr(
        autotune, "_time_best",
        _deterministic_times([1.0, 2.0, 1.0, 0.5, 1.0]),
    )
    measured = autotune.calibrate_crossover(
        ladder=(512, 1024), n_queries=64, reps=1
    )
    _, f0 = autotune._sphere_mesh(512)
    assert measured == f0.shape[0]
    with open(autotune._cache_path()) as fh:
        blob = json.load(fh)
    assert blob["crossover_faces"] == measured
    assert len(blob["ladder"]) == 2
    # a fresh process (simulated by clearing the in-process cache) reads
    # the persisted measurement back
    monkeypatch.setattr(autotune, "_measured", None)
    assert autotune.crossover_faces() == measured


def test_unstable_backend_not_persisted(monkeypatch):
    # the stability recheck disagrees by >2x -> value used in-process but
    # never written (transient axon-tunnel degradation guard)
    monkeypatch.setattr(
        autotune, "_time_best",
        _deterministic_times([1.0, 2.0, 1.0, 0.5, 10.0]),
    )
    measured = autotune.calibrate_crossover(
        ladder=(512, 1024), n_queries=64, reps=1
    )
    assert measured > 0
    import os
    assert not os.path.exists(autotune._cache_path())


def test_poisoned_cache_falls_back_to_default(monkeypatch):
    import os
    os.makedirs(os.path.dirname(autotune._cache_path()), exist_ok=True)
    with open(autotune._cache_path(), "w") as fh:
        fh.write('{"crossover_faces": null}')
    assert autotune.crossover_faces() == autotune.DEFAULT_CROSSOVER


def test_auto_uses_measured_crossover(monkeypatch):
    # force a tiny crossover: auto must take the culled path yet stay exact
    monkeypatch.setenv("MESH_TPU_BRUTE_MAX_FACES", "16")
    from .fixtures import icosphere

    v, f = icosphere(3)
    assert f.shape[0] > 16
    pts = np.random.RandomState(0).randn(50, 3).astype(np.float32)
    auto = closest_faces_and_points_auto(
        v.astype(np.float32), f.astype(np.int32), pts
    )
    ref = closest_faces_and_points(
        v.astype(np.float32), f.astype(np.int32), pts
    )
    np.testing.assert_allclose(
        auto["sqdist"], np.asarray(ref["sqdist"]), atol=1e-6
    )


def test_mxu_default_without_measurement():
    assert autotune.mxu_crossover_faces() == autotune.MXU_DEFAULT_CROSSOVER


def test_mxu_env_override_wins(monkeypatch):
    monkeypatch.setenv("MESH_TPU_MXU_CROSSOVER_FACES", "4321")
    assert autotune.mxu_crossover_faces() == 4321
    # malformed pin: warn and fall through to the default
    monkeypatch.setenv("MESH_TPU_MXU_CROSSOVER_FACES", "not-a-number")
    assert autotune.mxu_crossover_faces() == autotune.MXU_DEFAULT_CROSSOVER


def test_mxu_calibrate_persists_and_reloads(monkeypatch):
    # per ladder point: (t_vpu, t_mxu); MXU loses at ladder[0], wins at
    # ladder[1]; stability recheck agrees -> persist.  Crossover = the
    # smallest MXU-winning F (ladder[1]'s actual face count).
    monkeypatch.setattr(
        autotune, "_time_best",
        _deterministic_times([1.0, 2.0, 1.0, 0.5, 1.0]),
    )
    measured = autotune.calibrate_mxu_crossover(
        ladder=(512, 1024), n_queries=64, reps=1
    )
    _, f1 = autotune._sphere_mesh(1024)
    assert measured == f1.shape[0]
    with open(autotune._mxu_cache_path()) as fh:
        blob = json.load(fh)
    assert blob["mxu_crossover_faces"] == measured
    assert len(blob["ladder"]) == 2
    # a fresh process (simulated by clearing the in-process cache) reads
    # the persisted measurement back
    monkeypatch.setattr(autotune, "_mxu_measured", None)
    assert autotune.mxu_crossover_faces() == measured


def test_mxu_unstable_backend_not_persisted(monkeypatch):
    import os
    monkeypatch.setattr(
        autotune, "_time_best",
        _deterministic_times([1.0, 2.0, 1.0, 0.5, 10.0]),
    )
    measured = autotune.calibrate_mxu_crossover(
        ladder=(512, 1024), n_queries=64, reps=1
    )
    assert measured > 0
    assert not os.path.exists(autotune._mxu_cache_path())


def test_mxu_poisoned_cache_falls_back_to_default(monkeypatch):
    import os
    os.makedirs(os.path.dirname(autotune._mxu_cache_path()), exist_ok=True)
    with open(autotune._mxu_cache_path(), "w") as fh:
        fh.write('{"mxu_crossover_faces": null}')
    assert autotune.mxu_crossover_faces() == autotune.MXU_DEFAULT_CROSSOVER


def test_mxu_vpu_always_wins_returns_past_ladder(monkeypatch):
    monkeypatch.setattr(
        autotune, "_time_best",
        _deterministic_times([0.5, 1.0, 0.5, 1.0, 0.5]),
    )
    measured = autotune.calibrate_mxu_crossover(
        ladder=(512, 1024), n_queries=16, reps=1, save=False
    )
    _, f1 = autotune._sphere_mesh(1024)
    assert measured == 2 * f1.shape[0]


def test_brute_always_wins_returns_past_ladder(monkeypatch):
    # if the culled path never wins on the measured ladder, the crossover
    # lands past the ladder (brute keeps being chosen at measured sizes)
    calls = {"n": 0}

    def fake_time(fn, reps):
        calls["n"] += 1
        # calibrate times brute then culled per ladder point
        return 0.5 if calls["n"] % 2 == 1 else 1.0

    monkeypatch.setattr(autotune, "_time_best", fake_time)
    measured = autotune.calibrate_crossover(
        ladder=(512, 1024), n_queries=16, reps=1, save=False
    )
    assert measured > 1024
