"""Compiled-mode Pallas kernel tests on the real TPU chip.

The CPU suite exercises every Pallas kernel in interpret mode only
(test_pallas*.py); these tests assert the *compiled* kernels against the
plain-XLA reference path on the actual device — the coverage VERDICT.md
item 6 asked for.  They are excluded from the CPU suite (tests/conftest.py
forces a virtual CPU platform) and run via:

    MESH_TPU_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -m tpu

(the env var makes conftest keep the default TPU backend).
"""

import numpy as np
import pytest

from tests.fixtures import separated_sphere_queries as _separated_sphere_queries

pytestmark = pytest.mark.tpu


def _on_tpu():
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


requires_tpu = pytest.mark.skipif(
    not _on_tpu(), reason="needs the real TPU backend (MESH_TPU_TEST_TPU=1)"
)


def _random_mesh(n_v=200, n_f=380, seed=0):
    rng = np.random.RandomState(seed)
    v = rng.randn(n_v, 3).astype(np.float32)
    f = rng.randint(0, n_v, size=(n_f, 3)).astype(np.int32)
    return v, f



@requires_tpu
class TestCompiledPallasParity:
    def test_closest_point_compiled_matches_xla(self):
        from mesh_tpu.query import closest_faces_and_points
        from mesh_tpu.query.pallas_closest import closest_point_pallas

        v, f = _random_mesh()
        rng = np.random.RandomState(1)
        pts = rng.randn(500, 3).astype(np.float32)
        out = closest_point_pallas(v, f, pts)                  # compiled
        ref = closest_faces_and_points(v, f, pts)
        # distinct argmin tie-breaks can pick different but equidistant
        # faces; the distances must match everywhere
        d_p = np.linalg.norm(np.asarray(out["point"]) - pts, axis=1)
        d_r = np.linalg.norm(np.asarray(ref["point"]) - pts, axis=1)
        np.testing.assert_allclose(d_p, d_r, atol=1e-5)
        # the random mesh has many near-coincident triangles, so a few
        # argmin ties legitimately break differently; the distance check
        # above is the exact assertion
        agree = (np.asarray(out["face"]) == np.asarray(ref["face"])).mean()
        assert agree > 0.9, f"face agreement only {agree:.3f}"

    def test_culled_compiled_matches_xla(self):
        from mesh_tpu.query import closest_faces_and_points
        from mesh_tpu.query.pallas_culled import closest_point_pallas_culled

        v, f = _random_mesh(n_v=400, n_f=800, seed=2)
        rng = np.random.RandomState(3)
        pts = rng.randn(600, 3).astype(np.float32)
        out = closest_point_pallas_culled(v, f, pts)
        ref = closest_faces_and_points(v, f, pts)
        d_c = np.linalg.norm(np.asarray(out["point"]) - pts, axis=1)
        d_r = np.linalg.norm(np.asarray(ref["point"]) - pts, axis=1)
        np.testing.assert_allclose(d_c, d_r, atol=1e-5)

    def test_normal_weighted_compiled_matches_xla(self):
        from mesh_tpu.query import nearest_normal_weighted
        from mesh_tpu.query.pallas_normal_weighted import (
            nearest_normal_weighted_pallas,
        )

        v, f = _random_mesh(seed=4)
        rng = np.random.RandomState(5)
        pts = rng.randn(300, 3).astype(np.float32)
        nrm = rng.randn(300, 3).astype(np.float32)
        nrm /= np.linalg.norm(nrm, axis=1, keepdims=True)
        face_p, point_p = nearest_normal_weighted_pallas(v, f, pts, nrm, eps=0.1)
        face_r, point_r = nearest_normal_weighted(v, f, pts, nrm, eps=0.1)
        agree = (np.asarray(face_p) == np.asarray(face_r)).mean()
        assert agree > 0.99, f"face agreement only {agree:.3f}"
        same = np.asarray(face_p) == np.asarray(face_r)
        np.testing.assert_allclose(
            np.asarray(point_p)[same], np.asarray(point_r)[same], atol=1e-4
        )

    def test_visibility_compiled_matches_xla(self):
        """visibility_compute routes through the compiled any-hit kernel
        on TPU; its blocked/n_dot_cam outputs must match the XLA path."""
        import jax.numpy as jnp

        from mesh_tpu.query.visibility import (
            _visibility_kernel, _visibility_kernel_pallas,
        )

        v, f = _random_mesh(n_v=300, n_f=560, seed=8)
        vj = jnp.asarray(v)
        tri = vj[jnp.asarray(f)]
        cams = jnp.asarray([[4.0, 0.0, 0.0], [0.0, 0.0, -4.0]], jnp.float32)
        normals = jnp.asarray(
            v / np.linalg.norm(v, axis=1, keepdims=True), jnp.float32
        )
        vis_p, ndc_p = _visibility_kernel_pallas(          # compiled
            vj, tri, cams, normals, None, jnp.float32(1e-3)
        )
        vis_x, ndc_x = _visibility_kernel(
            vj, tri[:, 0], tri[:, 1], tri[:, 2], cams, normals, None,
            jnp.float32(1e-3),
        )
        np.testing.assert_array_equal(np.asarray(vis_p), np.asarray(vis_x))
        np.testing.assert_allclose(
            np.asarray(ndc_p), np.asarray(ndc_x), atol=1e-6
        )

    def test_nearest_vertices_compiled_matches_xla(self):
        from mesh_tpu.query.closest_point import _closest_vertices_xla
        from mesh_tpu.query.pallas_closest import nearest_vertices_pallas

        v, _ = _random_mesh(seed=18)
        rng = np.random.RandomState(19)
        q = rng.randn(400, 3).astype(np.float32)
        i_p, d_p = nearest_vertices_pallas(v, q)          # compiled
        i_x, d_x = _closest_vertices_xla(v, q)
        np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_x),
                                   atol=1e-5)

    def test_nearest_alongnormal_compiled_matches_xla(self):
        from mesh_tpu.query.pallas_ray import nearest_alongnormal_pallas
        from mesh_tpu.query.ray import _nearest_alongnormal_xla

        v, f = _random_mesh(seed=9)
        rng = np.random.RandomState(10)
        pts = rng.randn(200, 3).astype(np.float32)
        nrm = rng.randn(200, 3).astype(np.float32)
        d_p, f_p, p_p = nearest_alongnormal_pallas(v, f, pts, nrm)
        d_x, f_x, p_x = _nearest_alongnormal_xla(v, f, pts, nrm)
        np.testing.assert_allclose(
            np.asarray(d_p), np.asarray(d_x), atol=1e-4
        )
        same = np.asarray(f_p) == np.asarray(f_x)
        np.testing.assert_allclose(
            np.asarray(p_p)[same], np.asarray(p_x)[same], atol=1e-4
        )

    def test_tri_tri_compiled_matches_xla(self):
        from mesh_tpu.query.ray import (
            _intersections_mask_pallas, _intersections_mask_xla,
        )

        v, f = _random_mesh(n_v=150, n_f=300, seed=11)
        qv, qf = _random_mesh(n_v=80, n_f=150, seed=12)
        qv = qv * 0.7 + np.array([0.5, 0, 0], np.float32)
        out = np.asarray(_intersections_mask_pallas(v, f, qv, qf))
        ref = np.asarray(_intersections_mask_xla(v, f, qv, qf))
        np.testing.assert_array_equal(out, ref)

    def test_self_intersection_compiled_matches_xla(self):
        from mesh_tpu.query.pallas_ray import self_intersection_count_pallas
        from mesh_tpu.query.ray import _self_intersection_count_xla

        v, f = _random_mesh(n_v=120, n_f=240, seed=13)
        out = int(self_intersection_count_pallas(v, f))
        ref = int(_self_intersection_count_xla(v, f))
        assert out == ref
        assert ref > 0    # a random triangle soup self-intersects a lot

    def test_sharded_paths_run_pallas_per_shard(self):
        """shard_map composes with the Pallas kernels on TPU: the sharded
        closest-point and visibility entry points must agree with the
        unsharded kernels on a 1-device mesh (the multi-device form is
        covered by the virtual-CPU suite, which takes the XLA branch)."""
        from mesh_tpu.parallel.sharding import (
            make_device_mesh, sharded_closest_faces_and_points,
            sharded_closest_faces_sharded_topology, sharded_visibility,
        )
        from mesh_tpu.query import closest_faces_and_points
        from mesh_tpu.query.visibility import visibility_compute

        v, f = _random_mesh(seed=14)
        rng = np.random.RandomState(15)
        pts = rng.randn(200, 3).astype(np.float32)
        mesh = make_device_mesh(n_devices=1, axis_names=("dp",))
        ref = closest_faces_and_points(v, f, pts)
        out = sharded_closest_faces_and_points(v, f, pts, mesh)
        np.testing.assert_allclose(
            np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )
        out_f = sharded_closest_faces_sharded_topology(v, f, pts, mesh)
        np.testing.assert_allclose(
            np.asarray(out_f["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )
        cams = np.array([[4.0, 0, 0]], np.float32)
        nrm = rng.randn(len(v), 3).astype(np.float32)
        vis_s, ndc_s = sharded_visibility(v, f, cams, n=nrm, mesh=mesh)
        vis_r, ndc_r = visibility_compute(v, f, cams, n=nrm)
        np.testing.assert_array_equal(vis_s, vis_r)
        np.testing.assert_allclose(ndc_s, ndc_r, atol=1e-5)

    def test_aabb_tree_facade_takes_pallas_branch_on_tpu(self):
        """AabbTree.nearest routes through closest_faces_and_points_auto,
        whose TPU branch runs the Pallas kernels; results must match the
        XLA reference and keep the reference's (1, S) return shapes."""
        from mesh_tpu import Mesh
        from mesh_tpu.query import closest_faces_and_points

        v, f = _random_mesh(seed=16)
        m = Mesh(v=np.asarray(v, np.float64), f=f.astype(np.uint32))
        tree = m.compute_aabb_tree()
        rng = np.random.RandomState(17)
        pts = rng.randn(150, 3)
        f_idx, f_part, points = tree.nearest(pts, nearest_part=True)
        assert f_idx.shape == (1, 150) and f_part.shape == (1, 150)
        ref = closest_faces_and_points(
            v, f, np.asarray(pts, np.float32)
        )
        d_t = np.linalg.norm(points - pts, axis=1)
        d_r = np.linalg.norm(np.asarray(ref["point"]) - pts, axis=1)
        np.testing.assert_allclose(d_t, d_r, atol=1e-5)

    def test_search_facade_takes_pallas_branch_on_tpu(self):
        """search.py AabbNormalsTree routes to the compiled Pallas kernel
        when the backend is TPU — exercise that exact branch."""
        from mesh_tpu import Mesh
        from mesh_tpu.query import nearest_normal_weighted

        v, f = _random_mesh(seed=6)
        m = Mesh(v=np.asarray(v, np.float64), f=f.astype(np.uint32))
        tree = m.compute_aabb_normals_tree()
        rng = np.random.RandomState(7)
        pts = rng.randn(100, 3)
        nrm = rng.randn(100, 3)
        nrm /= np.linalg.norm(nrm, axis=1, keepdims=True)
        face_t, point_t = tree.nearest(pts, nrm)
        assert face_t.shape == (100, 1)           # reference return shape
        face_r, _ = nearest_normal_weighted(
            np.asarray(v), f, np.asarray(pts, np.float32),
            np.asarray(nrm, np.float32), eps=0.1,
        )
        agree = (face_t.ravel() == np.asarray(face_r).ravel()).mean()
        assert agree > 0.99

    # ------------------------------------------------------------------
    # round-3 additions: every new TPU-affecting path gets a compiled test

    def test_force_xla_escape_hatch_matches_pallas(self, monkeypatch):
        """MESH_TPU_FORCE_XLA=1 must route to the XLA paths ON the chip
        and agree with the default Pallas dispatch."""
        from mesh_tpu.query.closest_point import (
            closest_vertices_with_distance,
        )
        from mesh_tpu.utils.dispatch import pallas_default

        v, _ = _random_mesh(seed=20)
        rng = np.random.RandomState(21)
        pts = rng.randn(200, 3).astype(np.float32)
        monkeypatch.delenv("MESH_TPU_FORCE_XLA", raising=False)
        assert pallas_default() is True
        idx_pallas, d_pallas = closest_vertices_with_distance(v, pts)
        monkeypatch.setenv("MESH_TPU_FORCE_XLA", "1")
        assert pallas_default() is False
        idx_xla, d_xla = closest_vertices_with_distance(v, pts)
        np.testing.assert_allclose(
            np.asarray(d_pallas), np.asarray(d_xla), atol=1e-5
        )
        agree = (np.asarray(idx_pallas) == np.asarray(idx_xla)).mean()
        assert agree > 0.99

    def test_batched_facade_vmapped_pallas(self):
        """mesh_tpu.batch lifts the Pallas grid over the mesh batch; the
        one-dispatch result must match per-mesh facade calls compiled."""
        from mesh_tpu import Mesh, fused_normals_and_closest_points

        v, f = _random_mesh(seed=22)
        rng = np.random.RandomState(23)
        meshes = [
            Mesh(v=np.asarray(v, np.float64) * (1 + 0.1 * k)
                 + 0.01 * rng.randn(*v.shape), f=f.astype(np.uint32))
            for k in range(3)
        ]
        pts = rng.randn(100, 3).astype(np.float32)
        normals, faces, points = fused_normals_and_closest_points(
            meshes, pts
        )
        for k, m in enumerate(meshes):
            np.testing.assert_allclose(
                normals[k], m.estimate_vertex_normals(), atol=1e-5
            )
            _, p_ref = m.closest_faces_and_points(pts)
            d_b = np.linalg.norm(points[k] - pts, axis=1)
            d_r = np.linalg.norm(p_ref - pts, axis=1)
            np.testing.assert_allclose(d_b, d_r, atol=1e-5)

    def test_calibrate_crossover_on_chip(self, monkeypatch, tmp_path):
        """The brute-vs-culled calibration must run compiled and produce a
        usable threshold (its ladder exercises both Pallas kernels)."""
        import mesh_tpu
        from mesh_tpu.query import autotune

        monkeypatch.setattr(autotune, "_measured", None)
        monkeypatch.setattr(
            mesh_tpu, "mesh_package_cache_folder", str(tmp_path)
        )
        measured = autotune.calibrate_crossover(
            ladder=(4096, 16384), n_queries=256, reps=2
        )
        assert measured > 0

    def test_large_f_culled_exact_compiled(self):
        """The tile-sphere-culled kernel must stay exact at a face count
        past any calibrated crossover (the config-6 regime, shrunk)."""
        from mesh_tpu.query.autotune import _sphere_mesh
        from mesh_tpu.query.pallas_closest import closest_point_pallas
        from mesh_tpu.query.pallas_culled import closest_point_pallas_culled

        v, f = _sphere_mesh(120_000)
        rng = np.random.RandomState(24)
        pts = rng.randn(512, 3).astype(np.float32)
        brute = closest_point_pallas(v, f, pts)
        culled = closest_point_pallas_culled(v, f, pts)
        np.testing.assert_allclose(
            np.sqrt(np.asarray(culled["sqdist"])),
            np.sqrt(np.asarray(brute["sqdist"])),
            atol=1e-4,
        )

    def test_ring_merge_compiled_single_device(self):
        """The ring merge on a 1-device mesh degenerates to the local
        Pallas result — exercises the shard_map + fori_loop + ppermute
        composition compiled (multi-hop behavior is covered by the
        8-device CPU suite)."""
        import jax
        from jax.sharding import Mesh as JMesh

        from mesh_tpu.parallel import sharded_closest_faces_sharded_topology
        from mesh_tpu.query.pallas_closest import closest_point_pallas

        v, f = _random_mesh(seed=25)
        rng = np.random.RandomState(26)
        pts = rng.randn(128, 3).astype(np.float32)
        mesh = JMesh(np.asarray(jax.devices()[:1]), ("dp",))
        for merge in ("gather", "ring"):
            out = sharded_closest_faces_sharded_topology(
                v, f, pts, mesh, merge=merge
            )
            ref = closest_point_pallas(v, f, pts)
            np.testing.assert_allclose(
                out["sqdist"], np.asarray(ref["sqdist"]), atol=1e-5
            )

    def test_nearest_alongnormal_epilogue_compiled(self):
        """The shared-acceptance epilogue (round 3) must return a finite
        hit for every query whose kernel winner is a genuine hit —
        exercised compiled on borderline edge-on geometry."""
        from mesh_tpu.query.ray import nearest_alongnormal

        v = np.array(
            [[0, 0, 0], [1, 0, 0], [0, 1, 0], [1, 1, 0]], np.float32
        )
        f = np.array([[0, 1, 2], [1, 3, 2]], np.int32)
        pts = np.array(
            [[0.5, 0.5, -1.0], [0.3, 0.0, 2.0], [0.0, 0.0, -1.0]],
            np.float32,
        )
        nrm = np.array([[0, 0, 1], [0, 0, -1], [0, 0, 1]], np.float32)
        dist, face, point = nearest_alongnormal(v, f, pts, nrm)
        d = np.asarray(dist)
        assert np.all(np.isfinite(d)), d
        np.testing.assert_allclose(d, [1.0, 2.0, 1.0], atol=1e-5)

    def test_batched_facade_culled_routing_compiled(self, monkeypatch):
        """Above the crossover the batched facade runs the natively
        batched culled kernel; results must match the vmapped brute
        kernel compiled."""
        from mesh_tpu.batch import batched_closest_faces_and_points
        from mesh_tpu.query.autotune import _sphere_mesh

        v, f = _sphere_mesh(40_000)
        rng = np.random.RandomState(27)
        v_stack = np.stack([v, v * 1.1])
        pts = rng.randn(2, 256, 3).astype(np.float32)
        monkeypatch.setenv("MESH_TPU_BRUTE_MAX_FACES", "1000")  # force culled
        faces_c, points_c = batched_closest_faces_and_points(
            (v_stack, f), pts
        )
        monkeypatch.setenv("MESH_TPU_BRUTE_MAX_FACES", "10000000")  # brute
        faces_b, points_b = batched_closest_faces_and_points(
            (v_stack, f), pts
        )
        d_c = np.linalg.norm(points_c - pts, axis=-1)
        d_b = np.linalg.norm(points_b - pts, axis=-1)
        np.testing.assert_allclose(d_c, d_b, atol=1e-4)


@requires_tpu
class TestCompiledRound3Additions:
    """Compiled validation for paths added after the last on-chip window:
    the MXU-fed tile and the batched visibility dispatch (the
    dimension_semantics annotations are exercised by every kernel test in
    this file)."""

    def test_mxu_tile_compiled_matches_xla(self):
        from mesh_tpu.query import closest_faces_and_points
        from mesh_tpu.query.pallas_closest import closest_point_pallas_mxu

        v, f = _random_mesh()
        rng = np.random.RandomState(11)
        pts = rng.randn(500, 3).astype(np.float32)
        out = closest_point_pallas_mxu(v, f, pts)              # compiled
        ref = closest_faces_and_points(v, f, pts)
        d_p = np.linalg.norm(np.asarray(out["point"]) - pts, axis=1)
        d_r = np.linalg.norm(np.asarray(ref["point"]) - pts, axis=1)
        np.testing.assert_allclose(d_p, d_r, atol=1e-5)

    def test_batched_visibility_compiled(self):
        from mesh_tpu import Mesh, batched_vertex_visibility
        from mesh_tpu.query import visibility_compute

        rng = np.random.RandomState(3)
        # smooth parametric sphere (the soup mesh has no meaningful
        # self-occlusion structure)
        th = np.linspace(0.2, np.pi - 0.2, 12)
        ph = np.linspace(0, 2 * np.pi, 16, endpoint=False)
        grid = np.stack(np.meshgrid(th, ph, indexing="ij"), -1).reshape(-1, 2)
        v = np.stack([
            np.sin(grid[:, 0]) * np.cos(grid[:, 1]),
            np.sin(grid[:, 0]) * np.sin(grid[:, 1]),
            np.cos(grid[:, 0]),
        ], axis=1).astype(np.float32)
        faces = []
        for i in range(11):
            for j in range(16):
                a = i * 16 + j
                b = i * 16 + (j + 1) % 16
                faces += [[a, b, a + 16], [b, (b + 16) % (12 * 16), a + 16]]
        f = np.asarray(faces, np.int32) % len(v)
        meshes = [Mesh(v=v * s, f=f) for s in (1.0, 1.4)]
        cams = np.array([[0, 0, 4.0], [4.0, 0, 0]], np.float32)
        vis, ndc = batched_vertex_visibility(meshes, cams)     # compiled
        assert vis.shape == (2, 2, len(v))
        for k, m in enumerate(meshes):
            n = np.asarray(m.estimate_vertex_normals(), np.float32)
            ref_vis, ref_ndc = visibility_compute(
                np.asarray(m.v, np.float32), f, cams, n=n
            )
            np.testing.assert_array_equal(vis[k], np.asarray(ref_vis))
            np.testing.assert_allclose(ndc[k], np.asarray(ref_ndc),
                                       atol=1e-5)


class TestNondegenFastPathCompiled:
    """The assume_nondegenerate tile variant, compiled on the chip: must be
    bit-identical to the default tile on a clean mesh (the dropped
    degenerate-face override is the identity there) — the same Mosaic
    lowering risk class every other kernel variant gets compiled coverage
    for."""

    @requires_tpu
    def test_flag_parity_compiled(self):
        from mesh_tpu.query.pallas_closest import (
            closest_point_pallas,
            mesh_is_nondegenerate,
        )
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(3)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        assert mesh_is_nondegenerate(v, f)
        rng = np.random.RandomState(0)
        pts = rng.randn(2048, 3).astype(np.float32)
        base = closest_point_pallas(v, f, pts)
        fast = closest_point_pallas(v, f, pts, assume_nondegenerate=True)
        np.testing.assert_array_equal(np.asarray(base["face"]),
                                      np.asarray(fast["face"]))
        np.testing.assert_array_equal(np.asarray(base["sqdist"]),
                                      np.asarray(fast["sqdist"]))


class TestMollerTriTriCompiled:
    """The Möller interval tile, compiled on the chip: must agree with the
    compiled segment tile on clean geometry (the facade's auto choice
    between them must be invisible in results)."""

    @requires_tpu
    def test_moller_vs_segment_compiled(self):
        from mesh_tpu.query.pallas_ray import tri_tri_any_hit_pallas
        from mesh_tpu.sphere import _icosphere

        body_v, body_f = _icosphere(3)
        hand_v, hand_f = _icosphere(2)
        hand_v = hand_v * 0.25 + np.array([0.92, 0, 0])
        q_tri = hand_v.astype(np.float32)[hand_f]
        m_tri = body_v.astype(np.float32)[body_f]
        seg = np.asarray(tri_tri_any_hit_pallas(q_tri, m_tri,
                                                algorithm="segment"))
        mol = np.asarray(tri_tri_any_hit_pallas(q_tri, m_tri,
                                                algorithm="moller"))
        np.testing.assert_array_equal(seg, mol)
        assert seg.sum() > 0

    @requires_tpu
    def test_self_intersect_moller_vs_segment_compiled(self):
        from mesh_tpu.query.pallas_ray import self_intersection_count_pallas
        from tests.test_reference_fixtures import (
            SELF_INT_CYL_F,
            SELF_INT_CYL_V,
        )

        v = SELF_INT_CYL_V.astype(np.float32)
        f = SELF_INT_CYL_F.astype(np.int32)
        seg = int(self_intersection_count_pallas(v, f, algorithm="segment"))
        mol = int(self_intersection_count_pallas(v, f, algorithm="moller"))
        assert seg == mol == 2 * 8

    @requires_tpu
    def test_culled_flag_parity_compiled(self):
        from mesh_tpu.query.pallas_culled import closest_point_pallas_culled
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(3)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        rng = np.random.RandomState(1)
        pts = rng.randn(1024, 3).astype(np.float32)
        base = closest_point_pallas_culled(v, f, pts)
        fast = closest_point_pallas_culled(v, f, pts,
                                           assume_nondegenerate=True)
        np.testing.assert_array_equal(np.asarray(base["face"]),
                                      np.asarray(fast["face"]))
        np.testing.assert_array_equal(np.asarray(base["sqdist"]),
                                      np.asarray(fast["sqdist"]))

    @requires_tpu
    def test_sliver_safe_tile_compiled(self):
        """The direct-corner sliver-safe tile (round 5), compiled: same
        distances as the fast tile on clean geometry."""
        from mesh_tpu.query.pallas_closest import closest_point_pallas
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(3)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        pts = _separated_sphere_queries(1024, seed=30)
        fast = closest_point_pallas(v, f, pts)
        safe = closest_point_pallas(v, f, pts, tile_variant="safe")
        np.testing.assert_allclose(np.asarray(safe["sqdist"]),
                                   np.asarray(fast["sqdist"]), atol=1e-6)
        # flips only in near-edge tie bands (see test_tile_variants)
        flipped = np.asarray(safe["face"]) != np.asarray(fast["face"])
        assert flipped.mean() < 0.15, flipped.mean()
        np.testing.assert_allclose(
            np.asarray(safe["sqdist"], np.float64)[flipped],
            np.asarray(fast["sqdist"], np.float64)[flipped],
            rtol=1e-5, atol=1e-7)

    @requires_tpu
    def test_fused_reduction_compiled(self):
        """The packed single-pass min+argmin reduction (round 5),
        compiled: winners within the documented tie radius of the exact
        scaffold's, distances exact via the epilogue."""
        from mesh_tpu.query.pallas_closest import closest_point_pallas
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(3)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        pts = _separated_sphere_queries(1024, seed=31)
        exact = closest_point_pallas(v, f, pts, assume_nondegenerate=True)
        fused = closest_point_pallas(v, f, pts, assume_nondegenerate=True,
                                     reduction="fused")
        sq_e = np.asarray(exact["sqdist"], np.float64)
        sq_f = np.asarray(fused["sqdist"], np.float64)
        radius = 2.0 ** -(23 - 11)        # tile_f=2048 -> 11 masked bits
        assert np.all(sq_f <= sq_e * (1 + 4 * radius) + 1e-12)
        # the tie-radius clause is the contract; the rate check only
        # guards gross misrouting (flips live in sqrt(radius)-wide
        # near-edge tie bands, which are sizeable at 11 masked bits)
        agree = (np.asarray(fused["face"]) == np.asarray(exact["face"])).mean()
        assert agree > 0.5, agree

    @requires_tpu
    def test_moller_prescale_large_scale_compiled(self):
        """mm-scale coordinates through the compiled Möller tile (round-5
        overflow fix): decisions must match the segment tile, which
        operates on raw coordinates."""
        from mesh_tpu.query.pallas_ray import tri_tri_any_hit_pallas
        from mesh_tpu.sphere import _icosphere

        body_v, body_f = _icosphere(3)
        hand_v, hand_f = _icosphere(2)
        hand_v = hand_v * 0.25 + np.array([0.92, 0, 0])
        scale = np.float32(1.8e3)
        q_tri = (hand_v.astype(np.float32) * scale)[hand_f]
        m_tri = (body_v.astype(np.float32) * scale)[body_f]
        seg = np.asarray(tri_tri_any_hit_pallas(q_tri, m_tri,
                                                algorithm="segment"))
        mol = np.asarray(tri_tri_any_hit_pallas(q_tri, m_tri,
                                                algorithm="moller"))
        np.testing.assert_array_equal(seg, mol)
        assert seg.sum() > 0

    @requires_tpu
    def test_normal_weighted_flag_parity_compiled(self):
        from mesh_tpu.query.pallas_normal_weighted import (
            nearest_normal_weighted_pallas,
        )
        from mesh_tpu.sphere import _icosphere

        v, f = _icosphere(3)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        rng = np.random.RandomState(2)
        pts = rng.randn(512, 3).astype(np.float32)
        nrm = rng.randn(512, 3).astype(np.float32)
        base = nearest_normal_weighted_pallas(v, f, pts, nrm, eps=0.1)
        fast = nearest_normal_weighted_pallas(v, f, pts, nrm, eps=0.1,
                                              assume_nondegenerate=True)
        np.testing.assert_array_equal(np.asarray(base[0]),
                                      np.asarray(fast[0]))
