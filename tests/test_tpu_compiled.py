"""Compiled-mode Pallas kernel tests on the real TPU chip.

The CPU suite exercises every Pallas kernel in interpret mode only
(test_pallas*.py); these tests assert the *compiled* kernels against the
plain-XLA reference path on the actual device — the coverage VERDICT.md
item 6 asked for.  They are excluded from the CPU suite (tests/conftest.py
forces a virtual CPU platform) and run via:

    MESH_TPU_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -m tpu

(the env var makes conftest keep the default TPU backend).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.tpu


def _on_tpu():
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


requires_tpu = pytest.mark.skipif(
    not _on_tpu(), reason="needs the real TPU backend (MESH_TPU_TEST_TPU=1)"
)


def _random_mesh(n_v=200, n_f=380, seed=0):
    rng = np.random.RandomState(seed)
    v = rng.randn(n_v, 3).astype(np.float32)
    f = rng.randint(0, n_v, size=(n_f, 3)).astype(np.int32)
    return v, f


@requires_tpu
class TestCompiledPallasParity:
    def test_closest_point_compiled_matches_xla(self):
        from mesh_tpu.query import closest_faces_and_points
        from mesh_tpu.query.pallas_closest import closest_point_pallas

        v, f = _random_mesh()
        rng = np.random.RandomState(1)
        pts = rng.randn(500, 3).astype(np.float32)
        out = closest_point_pallas(v, f, pts)                  # compiled
        ref = closest_faces_and_points(v, f, pts)
        # distinct argmin tie-breaks can pick different but equidistant
        # faces; the distances must match everywhere
        d_p = np.linalg.norm(np.asarray(out["point"]) - pts, axis=1)
        d_r = np.linalg.norm(np.asarray(ref["point"]) - pts, axis=1)
        np.testing.assert_allclose(d_p, d_r, atol=1e-5)
        # the random mesh has many near-coincident triangles, so a few
        # argmin ties legitimately break differently; the distance check
        # above is the exact assertion
        agree = (np.asarray(out["face"]) == np.asarray(ref["face"])).mean()
        assert agree > 0.9, f"face agreement only {agree:.3f}"

    def test_culled_compiled_matches_xla(self):
        from mesh_tpu.query import closest_faces_and_points
        from mesh_tpu.query.pallas_culled import closest_point_pallas_culled

        v, f = _random_mesh(n_v=400, n_f=800, seed=2)
        rng = np.random.RandomState(3)
        pts = rng.randn(600, 3).astype(np.float32)
        out = closest_point_pallas_culled(v, f, pts)
        ref = closest_faces_and_points(v, f, pts)
        d_c = np.linalg.norm(np.asarray(out["point"]) - pts, axis=1)
        d_r = np.linalg.norm(np.asarray(ref["point"]) - pts, axis=1)
        np.testing.assert_allclose(d_c, d_r, atol=1e-5)

    def test_normal_weighted_compiled_matches_xla(self):
        from mesh_tpu.query import nearest_normal_weighted
        from mesh_tpu.query.pallas_normal_weighted import (
            nearest_normal_weighted_pallas,
        )

        v, f = _random_mesh(seed=4)
        rng = np.random.RandomState(5)
        pts = rng.randn(300, 3).astype(np.float32)
        nrm = rng.randn(300, 3).astype(np.float32)
        nrm /= np.linalg.norm(nrm, axis=1, keepdims=True)
        face_p, point_p = nearest_normal_weighted_pallas(v, f, pts, nrm, eps=0.1)
        face_r, point_r = nearest_normal_weighted(v, f, pts, nrm, eps=0.1)
        agree = (np.asarray(face_p) == np.asarray(face_r)).mean()
        assert agree > 0.99, f"face agreement only {agree:.3f}"
        same = np.asarray(face_p) == np.asarray(face_r)
        np.testing.assert_allclose(
            np.asarray(point_p)[same], np.asarray(point_r)[same], atol=1e-4
        )

    def test_search_facade_takes_pallas_branch_on_tpu(self):
        """search.py AabbNormalsTree routes to the compiled Pallas kernel
        when the backend is TPU — exercise that exact branch."""
        from mesh_tpu import Mesh
        from mesh_tpu.query import nearest_normal_weighted

        v, f = _random_mesh(seed=6)
        m = Mesh(v=np.asarray(v, np.float64), f=f.astype(np.uint32))
        tree = m.compute_aabb_normals_tree()
        rng = np.random.RandomState(7)
        pts = rng.randn(100, 3)
        nrm = rng.randn(100, 3)
        nrm /= np.linalg.norm(nrm, axis=1, keepdims=True)
        face_t, point_t = tree.nearest(pts, nrm)
        assert face_t.shape == (100, 1)           # reference return shape
        face_r, _ = nearest_normal_weighted(
            np.asarray(v), f, np.asarray(pts, np.float32),
            np.asarray(nrm, np.float32), eps=0.1,
        )
        agree = (face_t.ravel() == np.asarray(face_r).ravel()).mean()
        assert agree > 0.99
