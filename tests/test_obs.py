"""Unified observability subsystem (mesh_tpu.obs, doc/observability.md).

Covers the PR-2 tentpole contracts:

- registry semantics (labeled counters/gauges/histograms, kind conflicts,
  loss-free concurrent writes from the executor worker + facade threads);
- ``engine.stats()`` as an exact compatibility view over the registry;
- span gating (``MESH_TPU_OBS`` off -> the shared no-op singleton) and
  the acceptance span tree: one facade closest-point call yields
  facade -> engine.submit -> (plan hit|compile) -> dispatch with correct
  parent chains;
- exporters: JSON-lines (spans + final metrics line), Prometheus text,
  ascii tree.
"""

import json
import threading

import numpy as np
import pytest

from mesh_tpu import obs
from mesh_tpu.obs.metrics import Registry
from mesh_tpu.obs.trace import _NOOP, TRACER, span, timed_span, traced


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv("MESH_TPU_OBS", raising=False)
    obs.reset()
    yield
    obs.reset()


def _tetra_mesh():
    from mesh_tpu.mesh import Mesh

    return Mesh(
        v=np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, 1]], float),
        f=np.array([[0, 1, 2], [0, 1, 3], [0, 2, 3], [1, 2, 3]], np.uint32),
    )


# ----------------------------------------------------------------------
# registry


class TestRegistry:
    def test_counter_labels_and_total(self):
        r = Registry()
        c = r.counter("requests_total", "help")
        c.inc(op="a")
        c.inc(2, op="b")
        c.inc(op="a")
        assert c.value(op="a") == 2
        assert c.value(op="b") == 2
        assert c.total() == 4

    def test_gauge_set_and_set_max(self):
        r = Registry()
        g = r.gauge("depth")
        g.set(3)
        g.set_max(2)        # lower: ignored
        g.set_max(7)
        assert g.value() == 7

    def test_histogram_stat_and_buckets(self):
        r = Registry()
        h = r.histogram("lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        s = h.stat()
        assert s["count"] == 3
        assert s["min"] == 0.05 and s["max"] == 5.0
        assert s["sum"] == pytest.approx(5.55)
        snap = r.snapshot()["lat"]["series"][0]
        # cumulative: <=0.1 holds 1, <=1.0 holds 2, +Inf holds all 3
        assert snap["buckets"] == [[0.1, 1], [1.0, 2], ["+Inf", 3]]

    def test_get_or_create_idempotent_and_kind_conflict(self):
        r = Registry()
        assert r.counter("x") is r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_reset_zeroes_everything(self):
        r = Registry()
        r.counter("c").inc(5)
        r.histogram("h").observe(1.0)
        r.reset()
        assert r.counter("c").total() == 0
        assert r.histogram("h").stat()["count"] == 0

    def test_concurrent_writers_lose_nothing(self):
        """Satellite (c): executor-worker + N facade threads hammering one
        counter and one histogram; the final snapshot is exact."""
        r = Registry()
        c = r.counter("hits_total")
        h = r.histogram("lat_s")
        n_threads, n_iter = 8, 500
        barrier = threading.Barrier(n_threads)

        def worker(tid):
            barrier.wait()
            for i in range(n_iter):
                c.inc(thread=tid % 2)
                h.observe(1e-4 * (i + 1))

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        # concurrent readers must never see a torn series
        for _ in range(50):
            snap = r.snapshot()
            assert set(snap) == {"hits_total", "lat_s"}
        for t in threads:
            t.join()
        assert c.total() == n_threads * n_iter
        assert h.stat()["count"] == n_threads * n_iter


class TestEngineStatsCompat:
    def test_snapshot_matches_registry(self):
        """Satellite (c): engine.stats() is a view — every number in the
        compat snapshot equals the registry series backing it."""
        from mesh_tpu import engine
        from mesh_tpu.engine.stats import STATS

        engine.reset_stats()
        STATS.record_plan_miss(0.25)
        STATS.record_plan_hit()
        STATS.record_plan_hit()
        STATS.record_padding(useful=30, padded=40)
        STATS.record_coalesced(3)
        STATS.record_dispatch("closest_point", 0.002)
        snap = engine.stats()
        reg = obs.REGISTRY
        assert snap["plan_cache"]["hits"] == reg.counter(
            "mesh_tpu_engine_plan_hits_total").value()
        assert snap["plan_cache"]["misses"] == reg.counter(
            "mesh_tpu_engine_plan_misses_total").value()
        assert snap["retraces"] == snap["plan_cache"]["misses"]
        assert snap["plan_cache"]["compile_seconds"] == 0.25
        assert snap["pad_waste"] == 0.25
        assert snap["coalesced"]["dispatches"] == 1
        assert snap["coalesced"]["requests"] == 3
        assert snap["coalesced"]["max_batch"] == 3
        lat = snap["dispatch_latency"]["closest_point"]
        hist = reg.histogram("mesh_tpu_engine_dispatch_seconds")
        # the series carries a backend label since the latency ledger
        # landed; the compat snapshot aggregates across backends
        assert lat["count"] == hist.stat(
            op="closest_point", backend="xla")["count"]
        assert lat["total_s"] == pytest.approx(0.002)

    def test_snapshot_shape_is_pinned(self):
        from mesh_tpu import engine

        snap = engine.stats()
        assert set(snap) == {
            "plan_cache", "retraces", "pad_waste", "coalesced",
            "dispatch_latency",
        }

    def test_reset_is_safe_and_locked(self):
        # satellite (a): the lock exists before reset() and is taken
        # unconditionally — a fresh instance must construct cleanly and
        # reset concurrently without error
        from mesh_tpu.engine.stats import EngineStats

        s = EngineStats(registry=Registry())
        s.record_plan_hit()
        threads = [threading.Thread(target=s.reset) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert s.snapshot()["plan_cache"]["hits"] == 0


# ----------------------------------------------------------------------
# spans


class TestSpanGating:
    def test_off_by_default_returns_noop_singleton(self):
        s = span("anything", key=1)
        assert s is _NOOP
        with s as inner:
            inner.set(more=2)
        assert TRACER.events() == []

    def test_on_records_nested_spans(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        with span("outer") as o:
            with span("inner", k=2):
                pass
            o.set(done=True)
        ev = TRACER.events()
        names = {e["name"]: e for e in ev}
        assert set(names) == {"outer", "inner"}
        assert names["inner"]["parent_id"] == names["outer"]["span_id"]
        assert names["outer"]["parent_id"] is None
        assert names["outer"]["attrs"]["done"] is True
        assert names["inner"]["elapsed_s"] >= 0

    def test_error_status_on_exception(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        with pytest.raises(ValueError):
            with span("boom"):
                raise ValueError("nope")
        (ev,) = TRACER.events()
        assert ev["status"] == "error"
        assert ev["attrs"]["error"] == "ValueError"

    def test_falsey_env_values_stay_off(self, monkeypatch):
        for off in ("0", "false", "no", "off", ""):
            monkeypatch.setenv("MESH_TPU_OBS", off)
            assert span("x") is _NOOP

    def test_timed_span_measures_even_when_off(self):
        with timed_span("d") as t:
            pass
        assert t.elapsed is not None and t.elapsed >= 0
        assert TRACER.events() == []

    def test_traced_decorator(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_OBS", "1")

        @traced
        def add(a, b):
            return a + b

        assert add(2, 3) == 5
        (ev,) = TRACER.events()
        assert ev["name"].endswith("add")


class TestSpanTreeAcceptance:
    def test_facade_call_produces_full_chain(self, monkeypatch):
        """ISSUE acceptance: with MESH_TPU_OBS=1 a single facade
        closest-point call produces at least
        facade -> engine.submit -> (plan hit|compile) -> dispatch."""
        monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        m = _tetra_mesh()
        pts = np.random.RandomState(0).rand(37, 3)
        faces, cps = m.closest_faces_and_points(pts)
        assert faces.shape == (1, 37) and cps.shape == (37, 3)
        ev = {e["name"]: e for e in TRACER.events()}
        assert {"facade.closest_faces_and_points", "engine.submit",
                "engine.plan", "engine.dispatch"} <= set(ev)
        facade = ev["facade.closest_faces_and_points"]
        submit = ev["engine.submit"]
        plan = ev["engine.plan"]
        disp = ev["engine.dispatch"]
        # parent chain: facade is the root of the others
        assert facade["parent_id"] is None
        assert submit["parent_id"] == facade["span_id"]
        assert plan["parent_id"] == submit["span_id"]
        assert disp["parent_id"] == submit["span_id"]
        assert plan["attrs"]["outcome"] in ("hit", "compile")
        # and it all exports as JSON lines + renders as a tree
        tree = obs.render_tree()
        assert "facade.closest_faces_and_points" in tree
        assert "engine.submit" in tree


# ----------------------------------------------------------------------
# exporters


class TestExporters:
    def test_write_jsonl_spans_plus_metrics_line(self, monkeypatch,
                                                 tmp_path):
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        obs.counter("exported_total").inc(3)
        with span("a"):
            pass
        path = tmp_path / "out.jsonl"
        n = obs.write_jsonl(str(path))
        lines = [json.loads(line) for line in path.read_text().splitlines()]
        assert n == len(lines) == 2
        assert lines[0]["kind"] == "span" and lines[0]["name"] == "a"
        assert lines[-1]["kind"] == "metrics"
        assert lines[-1]["metrics"]["exported_total"]["series"][0][
            "value"] == 3

    def test_prometheus_text(self):
        obs.counter("prom_total", "a counter").inc(2, op="x")
        obs.histogram("prom_lat", buckets=(0.5,)).observe(0.1)
        text = obs.prometheus_text()
        assert "# TYPE prom_total counter" in text
        assert 'prom_total{op="x"} 2' in text
        assert 'prom_lat_bucket{le="0.5"} 1' in text
        assert 'prom_lat_bucket{le="+Inf"} 1' in text
        assert "prom_lat_count 1" in text

    def test_render_tree_empty_hint(self):
        assert "MESH_TPU_OBS" in obs.render_tree()

    def test_jsonl_sink_streams_live(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        path = tmp_path / "live.jsonl"
        sink = obs.jsonl_sink(str(path))
        TRACER.add_sink(sink)
        try:
            with span("streamed"):
                pass
        finally:
            TRACER.remove_sink(sink)
        (line,) = path.read_text().splitlines()
        assert json.loads(line)["name"] == "streamed"

    def test_prometheus_conformance(self):
        """Text-format spec: label values escape backslash, quote, and
        newline; HELP escapes backslash and newline; histograms carry
        the +Inf bucket and _sum/_count with bucket counts cumulative."""
        obs.counter("conf_total", 'help with \\ and\nnewline').inc(
            1, path='a\\b', msg='say "hi"\nbye')
        obs.histogram("conf_lat", "lat", buckets=(0.1, 1.0)).observe(0.05)
        obs.histogram("conf_lat").observe(0.5)
        obs.histogram("conf_lat").observe(99.0)
        text = obs.prometheus_text()
        assert "# HELP conf_total help with \\\\ and\\nnewline" in text
        line = next(l for l in text.splitlines()
                    if l.startswith("conf_total{"))
        assert '\\\\b' in line and '\\"hi\\"' in line and '\\nbye' in line
        assert "\n" not in line  # the escaped newline stayed escaped
        assert 'conf_lat_bucket{le="0.1"} 1' in text
        assert 'conf_lat_bucket{le="1.0"} 2' in text
        assert 'conf_lat_bucket{le="+Inf"} 3' in text
        assert "conf_lat_count 3" in text
        assert "conf_lat_sum 99.55" in text

    def test_jsonl_sink_rotates_at_size_bound(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        path = tmp_path / "bounded.jsonl"
        # ~1 KB cap: a handful of spans per file, several rotations
        sink = obs.jsonl_sink(str(path), max_mb=0.001, keep=2)
        TRACER.add_sink(sink)
        try:
            for i in range(60):
                with span("rotated", i=i, pad="x" * 120):
                    pass
        finally:
            TRACER.remove_sink(sink)
        rotated = sorted(p.name for p in tmp_path.iterdir())
        assert rotated == ["bounded.jsonl", "bounded.jsonl.1",
                           "bounded.jsonl.2"]
        # keep-N means older generations were dropped, and every
        # surviving file is under the bound and valid JSON lines
        for p in tmp_path.iterdir():
            assert p.stat().st_size <= 1100
            for line in p.read_text().splitlines():
                assert json.loads(line)["name"] == "rotated"
        # newest events are in the live file, oldest surviving in .2
        last_live = json.loads(
            path.read_text().splitlines()[-1])["attrs"]["i"]
        first_old = json.loads((tmp_path / "bounded.jsonl.2").read_text()
                               .splitlines()[0])["attrs"]["i"]
        assert last_live == 59 and first_old < last_live

    def test_jsonl_sink_rotation_env_gate(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        monkeypatch.setenv("MESH_TPU_OBS_JSONL_MAX_MB", "0.001")
        monkeypatch.setenv("MESH_TPU_OBS_JSONL_KEEP", "1")
        path = tmp_path / "env.jsonl"
        sink = obs.jsonl_sink(str(path))
        TRACER.add_sink(sink)
        try:
            for i in range(40):
                with span("env_rotated", i=i, pad="y" * 120):
                    pass
        finally:
            TRACER.remove_sink(sink)
        names = sorted(p.name for p in tmp_path.iterdir())
        assert names == ["env.jsonl", "env.jsonl.1"]


# ----------------------------------------------------------------------
# executor integration


class TestExecutorObservability:
    def test_queue_wait_recorded_per_request(self, monkeypatch):
        monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
        from mesh_tpu import engine
        from mesh_tpu.engine.executor import get_executor

        engine.reset_stats()
        m = _tetra_mesh()
        pts = np.random.RandomState(1).rand(16, 3).astype(np.float32)
        ex = get_executor()
        with ex.coalesce():
            futures = [
                ex.submit("closest_point", m, pts) for _ in range(3)
            ]
        for fut in futures:
            faces, cps = fut.result(timeout=120)
            assert cps.shape == (16, 3)
        hist = obs.REGISTRY.histogram("mesh_tpu_engine_queue_wait_seconds")
        assert hist.stat()["count"] == 3
        snap = engine.stats()
        assert snap["coalesced"]["requests"] == 3
        assert snap["coalesced"]["dispatches"] == 1
