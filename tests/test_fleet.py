"""Fleet serving fabric acceptance (doc/fleet.md).

The contract under test:

- the consistent-hash ring is deterministic across instances and
  processes, and removing a member remaps ONLY that member's keys;
- the routing key family (op | topology digest | shape bucket) matches
  the engine's plan-cache identity — ROUTER_Q_LADDER is pinned equal
  to engine.Q_LADDER;
- the router gives perfect digest affinity under stable membership,
  spills exactly one hop on queue_full (and only then), ejects
  DRAINING replicas without touching the survivors' keys, propagates
  every other rejection unchanged, and logs a deterministic per-replica
  admission checksum;
- MESH_TPU_FLEET=0 is a direct pass-through to the first replica (no
  fleet series, no admission log);
- routing paths stay ledger-clean: a router rejection closes/opens no
  ledger rows, a served request closes exactly one (LED001);
- trace replay through the router is deterministic (same trace + same
  membership => same replica_checksums);
- the coordinator's sink aggregation sums counters per label set and
  merges histograms bucket-wise; step() is fake-clock deterministic,
  shrink/release actuate through the audited tuning path, and
  grant_widen arbitrates (cooldown + pressure deny) with every verdict
  audited;
- the AOT tier indexes/verifies/quarantines through the store
  corruption funnel and never crashes;
- the sharded big-batch lane is bit-identical to the single-device
  path and counted, and stays off by default;
- `mesh-tpu fleet status` reads sinks jax-free with rc 0/2;
- the perfcheck fleet band hard-fails on affinity loss, spill drift,
  and checksum drift/absence.

Everything except the shard-lane test is jax-free and fake-clocked.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mesh_tpu.errors import ServeRejected
from mesh_tpu.fleet import (
    FleetCoordinator,
    FleetRouter,
    HashRing,
    aggregate_sinks,
    read_sink,
    routing_key,
    shape_bucket,
    topology_digest,
)
from mesh_tpu.fleet.router import ROUTER_Q_LADDER
from mesh_tpu.obs.ledger import get_ledger
from mesh_tpu.obs.metrics import REGISTRY, Registry
from mesh_tpu.obs.slo import SLO
from mesh_tpu.serve import (
    HealthMonitor,
    QueryService,
    Rung,
    ServeResult,
    run_trace_replay,
)
from mesh_tpu.utils import tuning

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PTS = np.zeros((4, 3), np.float32)
_FACES = np.zeros((1, 4), np.uint32)
_ANSWER = np.zeros((4, 3), np.float64)


# ---------------------------------------------------------------------------
# helpers


class FakeClock(object):
    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)
        return self.t


class FakeRecorder(object):
    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append((kind, fields))

    def trigger(self, *args, **kwargs):
        return None


class _Digest(object):
    """A mesh stand-in that is nothing but its routing identity."""

    def __init__(self, key):
        self.topology_key = key


def _replica(name, served=None, **kw):
    """A real QueryService on a plain-python ladder that tallies which
    digest each replica answered (the bench stage's idiom)."""

    def _ok(mesh, points, chunk, timeout):
        if served is not None:
            digest = getattr(mesh, "topology_key", str(mesh))
            counts = served.setdefault(name, {})
            counts[digest] = counts.get(digest, 0) + 1
        return ServeResult(_FACES, _ANSWER, "fleet-ok", certified=True)

    kw.setdefault("workers", 2)
    kw.setdefault("max_queue_per_tenant", 1024)
    return QueryService(ladder=[Rung("fleet-ok", _ok)],
                        health=HealthMonitor(watchdog=False),
                        default_deadline_s=30.0, **kw)


def _run_cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    return subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli"] + list(argv),
        capture_output=True, text=True, timeout=180, env=env, cwd=_REPO)


@pytest.fixture
def fleet_env(monkeypatch):
    """Clean fleet/tuner env + tuned state on both sides of a test."""
    for var in ("MESH_TPU_FLEET", "MESH_TPU_FLEET_SPILL",
                "MESH_TPU_FLEET_VNODES", "MESH_TPU_FLEET_AOT",
                "MESH_TPU_FLEET_SHARD", "MESH_TPU_FLEET_SHARD_MIN_Q",
                "MESH_TPU_TUNER", "MESH_TPU_SERVE_LADDER",
                "MESH_TPU_COALESCE_WINDOW_MS"):
        monkeypatch.delenv(var, raising=False)
    tuning.reset()
    yield monkeypatch
    tuning.reset()


# ---------------------------------------------------------------------------
# hash ring


def test_ring_deterministic_across_instances():
    members = ["r0", "r1", "r2", "r3"]
    a = HashRing(members)
    b = HashRing(list(members))
    keys = ["key-%03d" % i for i in range(100)]
    assert [a.lookup(k) for k in keys] == [b.lookup(k) for k in keys]
    for k in keys[:10]:
        order = a.choices(k)
        assert order[0] == a.lookup(k)
        assert sorted(order) == sorted(members)      # full dedup'd walk
        assert len(set(order)) == len(order)


def test_ring_removal_remaps_only_victims_keys():
    members = ["r0", "r1", "r2", "r3"]
    ring = HashRing(members)
    keys = ["digest-%04d" % i for i in range(200)]
    before = {k: ring.lookup(k) for k in keys}
    victim = "r2"
    ring.remove(victim)
    for k in keys:
        after = ring.lookup(k)
        if before[k] == victim:
            assert after != victim                   # victim's keys move
        else:
            assert after == before[k]                # nobody else's do
    # distribution sanity: every survivor still owns something
    owners = {ring.lookup(k) for k in keys}
    assert owners == {"r0", "r1", "r3"}


def test_ring_add_idempotent_and_remove_unknown():
    ring = HashRing(["a", "b"])
    ring.add("a")
    assert len(ring) == 2 and ring.members() == ["a", "b"]
    ring.remove("nope")                              # no-op, no raise
    assert "a" in ring and "nope" not in ring
    ring.remove("a")
    ring.remove("b")
    assert ring.lookup("anything") is None
    assert ring.choices("anything") == []


# ---------------------------------------------------------------------------
# routing key family


def test_shape_bucket_edges():
    assert shape_bucket(1) == ROUTER_Q_LADDER[0]
    assert shape_bucket(ROUTER_Q_LADDER[0]) == ROUTER_Q_LADDER[0]
    assert shape_bucket(ROUTER_Q_LADDER[0] + 1) == ROUTER_Q_LADDER[1]
    top = ROUTER_Q_LADDER[-1]
    assert shape_bucket(top) == top
    assert shape_bucket(top + 1) == 2 * top
    for bad in (0, -3):
        with pytest.raises(ValueError):
            shape_bucket(bad)


def test_router_ladder_pinned_to_engine():
    """The router restates the engine Q_LADDER to stay jax-free at
    import; the two tables (and their bucket arithmetic) must agree."""
    from mesh_tpu import engine

    assert tuple(ROUTER_Q_LADDER) == tuple(engine.Q_LADDER)
    for q in (1, 31, 32, 33, 500, 16384, 16385, 40000):
        assert shape_bucket(q) == engine.bucket_size(q, engine.Q_LADDER)


def test_topology_digest_chain():
    assert topology_digest("9ad31c55-v10-f20") == "9ad31c55-v10-f20"
    assert topology_digest(_Digest("my-key")) == "my-key"

    class _Raw(object):
        f = np.asarray([[0, 1, 2], [2, 1, 3]], np.int32)

    d = topology_digest(_Raw())
    assert d.startswith("crc32:") and d == topology_digest(_Raw())

    class _Other(object):
        f = np.asarray([[0, 1, 3]], np.int32)

    assert topology_digest(_Other()) != d


def test_routing_key_shape():
    key = routing_key("closest_point", _Digest("dg"), np.zeros((100, 3)))
    assert key == "closest_point|dg|128"


# ---------------------------------------------------------------------------
# router: affinity, determinism, kill switch


def test_affinity_and_checksum_determinism(fleet_env):
    digests = ["aff-digest-%02d" % i for i in range(8)]

    def _run():
        served = {}
        router = FleetRouter(recorder=FakeRecorder())
        for i in range(3):
            name = "aff-%d" % i
            router.add_replica(name, _replica(name, served))
        try:
            primaries = {
                d: router.plan("closest_point", _Digest(d), _PTS)[1][0]
                for d in digests}
            futures = [router.submit(_Digest(d), _PTS, tenant="t")
                       for _ in range(4) for d in digests]
            for fut in futures:
                fut.result(timeout=60.0)
            return served, primaries, router.admission_checksums()
        finally:
            router.stop(write_stats=False)

    served, primaries, sums = _run()
    # every digest was answered by exactly its ring primary, every time
    for d in digests:
        owners = [n for n, counts in served.items() if d in counts]
        assert owners == [primaries[d]]
        assert served[primaries[d]][d] == 4
    # same membership + same submit sequence => same checksums
    _, _, sums2 = _run()
    assert sums == sums2 and set(sums) == {"aff-0", "aff-1", "aff-2"}


def test_kill_switch_is_direct_passthrough(fleet_env):
    served = {}
    router = FleetRouter(recorder=FakeRecorder())
    for name in ("ks-first", "ks-second"):
        router.add_replica(name, _replica(name, served))
    try:
        # find a digest whose ring primary is NOT the first replica
        digest = next(
            d for d in ("ks-d%02d" % i for i in range(64))
            if router.plan("closest_point", _Digest(d), _PTS)[1][0]
            != "ks-first")
        fleet_env.setenv("MESH_TPU_FLEET", "0")
        router.submit(_Digest(digest), _PTS).result(timeout=60.0)
        assert digest in served.get("ks-first", {})      # ring bypassed
        assert "ks-second" not in served
        # nothing logged: no key, no ring, no fleet bookkeeping
        rows = {r["replica"]: r for r in router.status()["replicas"]}
        assert rows["ks-first"]["admitted"] == 0
        assert rows["ks-second"]["admitted"] == 0
    finally:
        router.stop(write_stats=False)


def test_spill_one_hop_on_queue_full(fleet_env):
    served = {}
    router = FleetRouter(recorder=FakeRecorder())
    for name in ("sp-a", "sp-b"):
        router.add_replica(
            name, _replica(name, served, workers=1, max_queue_per_tenant=1))
    try:
        mesh = _Digest("spill-digest")
        _key, order = router.plan("closest_point", mesh, _PTS)
        primary, sibling = order[0], order[1]
        spills0 = REGISTRY.counter("mesh_tpu_fleet_spill_total").value(
            replica=primary)
        services = router.replicas()
        services[primary].hold()            # fence: queue state is exact
        try:
            queued = router.submit(mesh, _PTS, tenant="st")   # fills q=1
            spilled = router.submit(mesh, _PTS, tenant="st")  # overflows
        finally:
            services[primary].release()
        queued.result(timeout=60.0)
        spilled.result(timeout=60.0)
        assert served[sibling]["spill-digest"] == 1       # one hop, landed
        assert served[primary]["spill-digest"] == 1
        assert REGISTRY.counter("mesh_tpu_fleet_spill_total").value(
            replica=primary) - spills0 == 1
    finally:
        router.stop(write_stats=False)


def test_spill_disabled_propagates_queue_full(fleet_env):
    fleet_env.setenv("MESH_TPU_FLEET_SPILL", "0")
    router = FleetRouter(recorder=FakeRecorder())
    for name in ("nsp-a", "nsp-b"):
        router.add_replica(
            name, _replica(name, workers=1, max_queue_per_tenant=1))
    try:
        mesh = _Digest("nospill-digest")
        primary = router.plan("closest_point", mesh, _PTS)[1][0]
        services = router.replicas()
        services[primary].hold()
        try:
            router.submit(mesh, _PTS, tenant="st")
            with pytest.raises(ServeRejected) as exc:
                router.submit(mesh, _PTS, tenant="st")
            assert exc.value.reason == "queue_full"
        finally:
            services[primary].release()
    finally:
        router.stop(write_stats=False)


def test_non_queue_full_rejection_never_spills(fleet_env):
    """Any rejection other than queue_full propagates unchanged even
    with a sibling available — the router adds no admission policy."""

    class _Rejecting(object):
        health = None

        def submit(self, *a, **kw):
            raise ServeRejected("shed", retry_after=1.0,
                                reason="low_priority")

        def stop(self, drain=True, write_stats=True):
            return None

    served = {}
    router = FleetRouter(recorder=FakeRecorder())
    router.add_replica("rej-a", _Rejecting())
    router.add_replica("rej-b", _replica("rej-b", served))
    try:
        # find a digest whose primary is the rejecting replica
        digest = next(
            d for d in ("rej-d%02d" % i for i in range(64))
            if router.plan("closest_point", _Digest(d), _PTS)[1][0]
            == "rej-a")
        with pytest.raises(ServeRejected) as exc:
            router.submit(_Digest(digest), _PTS)
        assert exc.value.reason == "low_priority"
        assert served == {}                      # sibling never consulted
    finally:
        router.stop(write_stats=False)


def test_drain_ejects_without_moving_survivor_keys(fleet_env):
    router = FleetRouter(recorder=FakeRecorder())
    replicas = {}
    for i in range(3):
        name = "ej-%d" % i
        replicas[name] = _replica(name)
        router.add_replica(name, replicas[name])
    try:
        digests = ["ej-digest-%02d" % i for i in range(30)]
        before = {
            d: router.plan("closest_point", _Digest(d), _PTS)[1][0]
            for d in digests}
        victim = before[digests[0]]
        replicas[victim].health.begin_drain()
        for d in digests:
            after = router.plan("closest_point", _Digest(d), _PTS)[1][0]
            if before[d] == victim:
                assert after != victim           # ejected from the order
            else:
                assert after == before[d]        # survivors untouched
        status = router.status()
        rows = {r["replica"]: r for r in status["replicas"]}
        assert rows[victim]["in_ring"] and not rows[victim]["eligible"]
    finally:
        router.stop(write_stats=False)


def test_remove_replica_returns_live_service(fleet_env):
    served = {}
    router = FleetRouter(recorder=FakeRecorder())
    router.add_replica("rm-a", _replica("rm-a", served))
    router.add_replica("rm-b", _replica("rm-b", served))
    service = router.remove_replica("rm-a")
    try:
        assert service is not None
        # NOT stopped: the owner drains it — it still serves directly
        service.submit(_Digest("direct"), _PTS).result(timeout=60.0)
        assert served["rm-a"]["direct"] == 1
        assert list(router.replicas()) == ["rm-b"]
        with pytest.raises(ValueError):
            router.add_replica("rm-b", service)  # dup name refused
    finally:
        service.stop(write_stats=False)
        router.stop(write_stats=False)


def test_empty_fleet_rejects(fleet_env):
    router = FleetRouter(recorder=FakeRecorder())
    with pytest.raises(ServeRejected) as exc:
        router.submit(_Digest("dg"), _PTS)
    assert exc.value.reason == "draining"


def test_router_paths_are_ledger_clean(fleet_env):
    """LED001 in vivo: a router rejection leaves no ledger rows at all;
    a served request closes exactly one (opened by the replica)."""
    router = FleetRouter(recorder=FakeRecorder())
    replica = _replica("led-a")
    router.add_replica("led-a", replica)
    try:
        replica.health.begin_drain()             # every submit rejects
        with pytest.raises(ServeRejected):
            router.submit(_Digest("led-dg"), _PTS, tenant="led-reject")
        rows = get_ledger().records()
        assert not any(r.get("tenant") == "led-reject" for r in rows)

        # recover is not modeled — use a fresh admitting replica
        router.remove_replica("led-a")
        router.add_replica("led-b", _replica("led-b"))
        n = 4
        futures = [router.submit(_Digest("led-dg"), _PTS,
                                 tenant="led-serve") for _ in range(n)]
        for fut in futures:
            fut.result(timeout=60.0)
        rows = get_ledger().records()
        closed = [r for r in rows if r.get("tenant") == "led-serve"]
        assert len(closed) == n                  # one close per admission
    finally:
        router.stop(write_stats=False)


# ---------------------------------------------------------------------------
# trace replay through the router


def test_trace_replay_through_router_is_deterministic(fleet_env):
    from mesh_tpu.obs import replay as obs_replay

    trace = obs_replay.synth_stampede(seed=11)
    reports = []
    for _ in range(2):
        t = [0.0]

        def sleep(dt):
            t[0] += max(dt, 0.0)

        router = FleetRouter(recorder=FakeRecorder())
        for i in range(3):
            name = "rp-%d" % i
            router.add_replica(
                name, _replica(name, max_queue_per_tenant=8192))
        try:
            reports.append(run_trace_replay(
                router, _Digest("replay-digest"), _PTS, trace,
                deadline_s=30.0, clock=lambda: t[0], sleep=sleep))
        finally:
            router.stop(write_stats=False)
    first, second = reports
    assert first["checksum"] == second["checksum"]
    assert first["replica_checksums"] == second["replica_checksums"]
    assert set(first["replica_checksums"]) == {"rp-0", "rp-1", "rp-2"}


# ---------------------------------------------------------------------------
# coordinator: sink aggregation


def test_aggregate_sinks_sums_counters_per_label_set():
    sink_a = {"metrics": {
        "mesh_tpu_serve_requests_total": {"type": "counter", "help": "h",
            "series": [
                {"labels": {"tenant": "t", "outcome": "ok"}, "value": 10},
                {"labels": {"tenant": "u", "outcome": "ok"}, "value": 1},
            ]}}}
    sink_b = {"metrics": {
        "mesh_tpu_serve_requests_total": {"type": "counter", "help": "h",
            "series": [
                {"labels": {"outcome": "ok", "tenant": "t"}, "value": 5},
            ]}}}
    agg = aggregate_sinks([sink_a, None, sink_b, {}])
    series = agg["mesh_tpu_serve_requests_total"]["series"]
    by_tenant = {s["labels"]["tenant"]: s["value"] for s in series}
    assert by_tenant == {"t": 15, "u": 1}


def test_aggregate_sinks_merges_histograms_bucketwise():
    mk = lambda count, total, lo, hi, b1, binf: {            # noqa: E731
        "type": "histogram", "help": "h", "series": [{
            "labels": {"tenant": "t"}, "count": count, "sum": total,
            "min": lo, "max": hi,
            "buckets": [[0.1, b1], ["+Inf", binf]]}]}
    agg = aggregate_sinks([
        {"metrics": {"mesh_tpu_serve_latency_seconds":
                     mk(4, 1.0, 0.01, 0.9, 3, 4)}},
        {"metrics": {"mesh_tpu_serve_latency_seconds":
                     mk(6, 2.0, 0.005, 0.5, 5, 6)}},
    ])
    row = agg["mesh_tpu_serve_latency_seconds"]["series"][0]
    assert row["count"] == 10 and row["sum"] == 3.0
    assert row["min"] == 0.005 and row["max"] == 0.9
    assert row["buckets"] == [[0.1, 8], ["+Inf", 10]]


def test_read_sink_paths_and_callables(tmp_path):
    path = tmp_path / "sink.json"
    path.write_text('{"health": {"state": "HEALTHY"}}')
    assert read_sink(str(path))["health"]["state"] == "HEALTHY"
    assert read_sink(str(tmp_path / "absent.json")) is None
    (tmp_path / "garbage.json").write_text("{nope")
    assert read_sink(str(tmp_path / "garbage.json")) is None
    assert read_sink(lambda: {"queues": {}}) == {"queues": {}}

    def _boom():
        raise RuntimeError("replica gone")

    assert read_sink(_boom) is None


# ---------------------------------------------------------------------------
# coordinator: fake-clock decisions, audit, arbitration


def _sink_state(good, total):
    return {"metrics": {
        "mesh_tpu_serve_requests_total": {
            "type": "counter", "help": "",
            "series": [{"labels": {"tenant": "t"}, "value": total}]},
        "mesh_tpu_serve_good_total": {
            "type": "counter", "help": "",
            "series": [{"labels": {"tenant": "t"}, "value": good}]},
    }}


def _drive_coordinator(recorder):
    """One deterministic shrink->release episode; returns (decisions,
    coordinator, registry)."""
    clock = FakeClock(100.0)
    state = {"good": 0, "total": 0}
    registry = Registry()
    coord = FleetCoordinator(
        {"replica-a": lambda: _sink_state(state["good"], state["total"]),
         "replica-b": lambda: _sink_state(0, 0)},
        objectives=[SLO("availability", "availability", 0.999)],
        clock=clock, recorder=recorder, registry=registry)
    decisions = [coord.step()["decision"]]           # no traffic: hold
    clock.advance(60.0)
    state.update(good=50, total=100)                 # 50% bad: fast burn
    decisions.append(coord.step()["decision"])
    clock.advance(10.0)
    decisions.append(coord.step()["decision"])       # still burning
    clock.advance(3640.0)                            # bad ages out of 1h
    state.update(good=1050, total=1100)              # good-only since
    decisions.append(coord.step()["decision"])
    return decisions, coord, registry


def test_coordinator_shrink_release_audited(fleet_env):
    fleet_env.setenv("MESH_TPU_TUNER", "1")
    recorder = FakeRecorder()
    decisions, coord, registry = _drive_coordinator(recorder)
    assert decisions == ["hold", "shrink", "shrink", "release"]
    assert tuning.get("serve_pre_trip") == 0         # released again
    # the actuations went through the audited knob path
    reasons = [e["reason"] for e in tuning.history_tail()
               if e.get("knob") == "serve_pre_trip"]
    assert any("fleet" in r for r in reasons)
    # every decision flight-recorded + counted on the private registry
    kinds = [k for k, _ in recorder.events]
    assert kinds.count("fleet_decision") == 4
    dec_counter = registry.counter(
        "mesh_tpu_fleet_coordinator_decisions_total")
    assert dec_counter.value(decision="shrink") == 2
    assert dec_counter.value(decision="release") == 1
    assert registry.gauge("mesh_tpu_fleet_sinks_readable").value() == 2
    # grant_widen is denied while the last observed pressure was high:
    # rewind to the shrink state via a fresh episode stopping mid-burn
    status = coord.status()
    assert status["pre_tripped"] is False


def test_coordinator_decisions_are_deterministic(fleet_env):
    fleet_env.setenv("MESH_TPU_TUNER", "1")
    first, _, _ = _drive_coordinator(FakeRecorder())
    tuning.reset()
    second, _, _ = _drive_coordinator(FakeRecorder())
    assert first == second


def test_coordinator_disabled_without_tuner(fleet_env):
    fleet_env.setenv("MESH_TPU_TUNER", "0")
    coord = FleetCoordinator({}, clock=FakeClock(),
                             recorder=FakeRecorder(), registry=Registry())
    assert coord.step() == {"decision": "disabled", "actions": []}


def test_grant_widen_cooldown_and_pressure(fleet_env):
    fleet_env.setenv("MESH_TPU_TUNER", "1")
    clock = FakeClock(0.0)
    recorder = FakeRecorder()
    registry = Registry()
    coord = FleetCoordinator({}, clock=clock, recorder=recorder,
                             registry=registry, widen_cooldown_s=30.0)
    assert coord.grant_widen(replica="r0") is True
    clock.advance(10.0)
    assert coord.grant_widen(replica="r1") is False  # cooldown
    clock.advance(25.0)
    assert coord.grant_widen(replica="r1") is True   # cooldown elapsed
    grants = registry.counter("mesh_tpu_fleet_widen_grants_total")
    assert grants.value(outcome="granted") == 2
    assert grants.value(outcome="denied") == 1
    reasons = [f["reason"] for k, f in recorder.events
               if k == "fleet_widen"]
    assert reasons == ["granted", "cooldown", "granted"]


def test_grant_widen_denied_under_fleet_pressure(fleet_env):
    fleet_env.setenv("MESH_TPU_TUNER", "1")
    clock = FakeClock(100.0)
    state = {"good": 0, "total": 0}
    recorder = FakeRecorder()
    coord = FleetCoordinator(
        {"replica-a": lambda: _sink_state(state["good"], state["total"])},
        objectives=[SLO("availability", "availability", 0.999)],
        clock=clock, recorder=recorder, registry=Registry())
    coord.step()
    clock.advance(60.0)
    state.update(good=50, total=100)
    assert coord.step()["decision"] == "shrink"      # pressure is high now
    clock.advance(100.0)
    assert coord.grant_widen(replica="r0") is False
    reasons = [f["reason"] for k, f in recorder.events
               if k == "fleet_widen"]
    assert reasons == ["fleet_pressure"]


# ---------------------------------------------------------------------------
# AOT executable tier (no compiles: pure file/CRC contract)


@pytest.fixture
def aot_store(tmp_path):
    from mesh_tpu.store.store import MeshStore

    store = MeshStore(root=str(tmp_path / "store"))
    from mesh_tpu.store import aot

    os.makedirs(aot.aot_xla_dir(store), exist_ok=True)
    yield store, aot
    # enable_aot_tier repoints the process-wide jax compilation cache;
    # put it back on the conftest-shared dir for the rest of the run
    from mesh_tpu.utils.compilation_cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()


def _seed_tier(store, aot, names=("a.bin", "sub/b.bin")):
    base = aot.aot_xla_dir(store)
    for i, rel in enumerate(names):
        path = os.path.join(base, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(b"executable-%d" % i * 64)
    return aot.index_aot(store)


def test_aot_index_verify_roundtrip(aot_store):
    store, aot = aot_store
    index = _seed_tier(store, aot)
    assert index["schema_version"] == aot.AOT_SCHEMA_VERSION
    assert set(index["files"]) == {"a.bin", os.path.join("sub", "b.bin")}
    assert aot.verify_aot(store) == []
    # the store-level audit folds the tier in
    assert store.verify() == []


def test_aot_verify_detects_drift_and_missing(aot_store):
    store, aot = aot_store
    _seed_tier(store, aot)
    base = aot.aot_xla_dir(store)
    with open(os.path.join(base, "a.bin"), "wb") as fh:
        fh.write(b"bitflip")
    os.remove(os.path.join(base, "sub", "b.bin"))
    corrupt0 = REGISTRY.counter("mesh_tpu_store_corrupt_total").value(
        what="aot_crc")
    problems = aot.verify_aot(store)
    assert len(problems) == 2
    assert any("CRC mismatch" in p for p in problems)
    assert any("missing" in p for p in problems)
    # every finding went through the store corruption funnel
    assert REGISTRY.counter("mesh_tpu_store_corrupt_total").value(
        what="aot_crc") - corrupt0 == 2
    assert store.verify() != []


def test_aot_fresh_tier_is_not_corruption(aot_store):
    store, aot = aot_store
    assert aot.verify_aot(store) == []               # no index: fresh


def test_aot_enable_quarantines_crc_drift(aot_store, fleet_env):
    store, aot = aot_store
    _seed_tier(store, aot)
    base = aot.aot_xla_dir(store)
    with open(os.path.join(base, "a.bin"), "wb") as fh:
        fh.write(b"bitflip")
    cache_dir = aot.enable_aot_tier(store=store, min_compile_secs=0.0)
    assert cache_dir == base
    assert not os.path.exists(os.path.join(base, "a.bin"))   # deleted
    assert os.path.exists(os.path.join(base, "sub", "b.bin"))  # kept
    # index re-snapshotted over the survivors
    index, problem = aot._read_index(store)
    assert problem is None
    assert set(index["files"]) == {os.path.join("sub", "b.bin")}
    assert aot.verify_aot(store) == []


def test_aot_enable_clears_tier_on_schema_mismatch(aot_store, fleet_env):
    store, aot = aot_store
    _seed_tier(store, aot)
    bad = {"schema_version": aot.AOT_SCHEMA_VERSION + 99, "files": {}}
    with open(aot.aot_index_path(store), "w") as fh:
        json.dump(bad, fh)
    corrupt0 = REGISTRY.counter("mesh_tpu_store_corrupt_total").value(
        what="aot_meta")
    cache_dir = aot.enable_aot_tier(store=store, min_compile_secs=0.0)
    assert cache_dir == aot.aot_xla_dir(store)
    # the whole tier was cleared; nothing crashed
    assert os.listdir(aot.aot_xla_dir(store)) == []
    assert REGISTRY.counter("mesh_tpu_store_corrupt_total").value(
        what="aot_meta") - corrupt0 == 1
    assert aot.verify_aot(store) == []               # fresh index, clean


def test_aot_enable_respects_kill_switch(aot_store, fleet_env):
    store, aot = aot_store
    fleet_env.setenv("MESH_TPU_FLEET_AOT", "0")
    _seed_tier(store, aot)
    base = aot.aot_xla_dir(store)
    with open(os.path.join(base, "a.bin"), "wb") as fh:
        fh.write(b"bitflip")
    assert aot.enable_aot_tier(store=store) is None
    # disabled = untouched: no quarantine, no index refresh
    assert os.path.exists(os.path.join(base, "a.bin"))


# ---------------------------------------------------------------------------
# sharded big-batch lane (the one jax-compiling test here)


def test_shard_lane_bit_identical_and_counted(fleet_env):
    # the lane lives in the EngineExecutor drain loop, so drive the
    # executor path directly (the jax-level facade bypasses coalescing)
    from mesh_tpu import Mesh, engine
    from mesh_tpu.sphere import _icosphere

    v, f = _icosphere(2)
    mesh = Mesh(v=v, f=f)
    pts = np.asarray(np.random.RandomState(9).randn(1500, 3), np.float32)
    counter = REGISTRY.counter("mesh_tpu_fleet_shard_dispatches_total")

    def _run():
        return engine.submit("closest_point", mesh, pts).result(timeout=120.0)

    # default: shard_min_q unset => lane off, nothing counted
    n0 = counter.value()
    faces_off, points_off = _run()
    assert counter.value() == n0

    # kill switch beats the pin: still the single-device path
    fleet_env.setenv("MESH_TPU_FLEET_SHARD_MIN_Q", "1024")
    fleet_env.setenv("MESH_TPU_FLEET_SHARD", "0")
    faces_kill, points_kill = _run()
    assert counter.value() == n0
    assert np.array_equal(faces_kill, faces_off)
    assert np.array_equal(points_kill, points_off)

    # lane on: counted, and bit-identical to the single-device path
    fleet_env.delenv("MESH_TPU_FLEET_SHARD")
    faces_on, points_on = _run()
    assert counter.value() == n0 + 1
    assert np.array_equal(faces_on, faces_off)
    assert np.array_equal(points_on, points_off)

    # below the threshold the lane never engages
    base_small = counter.value()
    engine.submit("closest_point", mesh, pts[:600]).result(timeout=120.0)
    assert counter.value() == base_small


# ---------------------------------------------------------------------------
# mesh-tpu fleet status (jax-free CLI)


def test_cli_fleet_status(tmp_path, fleet_env):
    sink_dir = tmp_path / "sinks"
    sink_dir.mkdir()
    healthy = _replica("cli-healthy")
    draining = _replica("cli-draining")
    try:
        healthy.submit(_Digest("cli-dg"), _PTS, tenant="t").result(
            timeout=60.0)
        healthy.write_stats(str(sink_dir / "replica-a.json"))
        draining.health.begin_drain()
        draining.write_stats(str(sink_dir / "replica-b.json"))
    finally:
        healthy.stop(write_stats=False)
        draining.stop(write_stats=False)
    (sink_dir / "replica-c.json").write_text("{truncated")

    proc = _run_cli("fleet", "status", "--dir", str(sink_dir), "--json")
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    rows = {r["replica"]: r for r in doc["replicas"]}
    assert rows["replica-a"]["readable"] and rows["replica-a"]["in_ring"]
    assert rows["replica-a"]["health"] == "healthy"
    assert rows["replica-b"]["health"] == "draining"
    assert not rows["replica-b"]["in_ring"]
    assert not rows["replica-c"]["readable"]
    assert doc["ring"]["members"] == ["replica-a"]

    # no readable sink at all: rc 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert _run_cli("fleet", "status", "--dir", str(empty)).returncode == 2


# ---------------------------------------------------------------------------
# perfcheck fleet band


_FLEET_GOLD = {
    "metric": "fleet_affinity", "value": 1.0, "warm_hit_rate": 0.875,
    "spills": 1, "checksum": 123456.0,
    "aot": {"warm_hits": 2, "speedup": 3.0},
}


def _fleet_band(cand, gold=_FLEET_GOLD):
    from mesh_tpu.obs.perf import perfcheck

    doc = {"fleet": cand} if cand is not None else \
        {"metric": "x", "value": None, "unit": None, "vs_baseline": None}
    return perfcheck(doc, fleet_golden={"fleet": dict(gold)})


def test_perfcheck_fleet_band():
    rc, lines = _fleet_band(dict(_FLEET_GOLD))
    assert rc == 0
    assert any("ok fleet routing affinity" in ln for ln in lines)
    # a candidate with no fleet record at all is a hard FAIL
    rc, lines = _fleet_band(None)
    assert rc == 1
    assert any("FAIL fleet" in ln for ln in lines)
    # affinity below the 0.95 hard floor fails regardless of tolerance
    rc, _ = _fleet_band(dict(_FLEET_GOLD, value=0.9))
    assert rc == 1
    # spill drift is exact-matched
    rc, lines = _fleet_band(dict(_FLEET_GOLD, spills=2))
    assert rc == 1
    assert any("FAIL fleet spills" in ln for ln in lines)
    # checksum drift is a hard FAIL even with everything else in band
    rc, lines = _fleet_band(dict(_FLEET_GOLD, checksum=123457.0))
    assert rc == 1
    assert any("FAIL fleet replica-admission checksum" in ln
               for ln in lines)
    # a candidate that cannot prove determinism is a hard FAIL
    no_sum = dict(_FLEET_GOLD)
    del no_sum["checksum"]
    rc, lines = _fleet_band(no_sum)
    assert rc == 1
    assert any("determinism unproven" in ln for ln in lines)
    # AOT warm start must actually hit the executable cache
    rc, _ = _fleet_band(dict(_FLEET_GOLD, aot={"warm_hits": 0,
                                               "speedup": 3.0}))
    assert rc == 1
    # record with no golden: informational note, rc 0
    from mesh_tpu.obs.perf import perfcheck

    rc, lines = perfcheck({"fleet": dict(_FLEET_GOLD)})
    assert rc == 0
    assert any("make fleet-golden" in ln for ln in lines)
