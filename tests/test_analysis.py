"""meshlint engine + rule-pack tests (ISSUE PR-8 tentpole).

Three layers:

- engine mechanics: fingerprints (line-free), baseline add/expire with
  reason preservation, the rc contract (clean=0 / new warning+=1 /
  baseline-only=0 / notes-never-block), the JSON report schema;
- per-rule fixtures: one positive and one negative snippet per rule id
  through ``engine.check_source`` (the fixture entry point), plus
  project-level fixtures (tmp trees) for the cross-file codes
  (KNB002, OBS001);
- the shipped tree: ``python -m mesh_tpu.cli lint --json`` in a
  subprocess must exit 0 with zero new findings in under 10 seconds —
  the gate-0 contract.

All of this is jax-free by design (the analyzer is stdlib-only).
"""

import json
import os
import subprocess
import sys
import textwrap

from mesh_tpu.analysis import engine
from mesh_tpu.analysis.engine import (
    Finding, Report, build_project, check_source, load_baseline,
    save_baseline,
)
from mesh_tpu.analysis.rules import all_rules
from mesh_tpu.analysis.rules.knb import KnobRegistryRule
from mesh_tpu.analysis.rules.lck import LockDisciplineRule
from mesh_tpu.analysis.rules.lok import LockOrderRule, parse_concurrency_doc
from mesh_tpu.analysis.rules.pal import PallasDmaRule
from mesh_tpu.analysis.rules.obs import ObservabilityHygieneRule
from mesh_tpu.analysis.rules.rcp import RecompileHazardRule
from mesh_tpu.analysis.rules.res import ResourcePathRule
from mesh_tpu.analysis.rules.led import LedgerLifecycleRule
from mesh_tpu.analysis.rules.flw import FlowSensitiveRule
from mesh_tpu.analysis.rules.trc import TracerLeakRule
from mesh_tpu.analysis.rules.vmem import VmemBudgetRule

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(findings):
    return [f.rule for f in findings]


def _run(rule, source):
    return check_source(rule, textwrap.dedent(source))


# -- engine mechanics --------------------------------------------------

def test_fingerprint_is_line_free_and_message_sensitive():
    a = Finding("TRC001", "error", "mesh_tpu/x.py", 10, "msg")
    b = Finding("TRC001", "error", "mesh_tpu/x.py", 999, "msg")
    c = Finding("TRC001", "error", "mesh_tpu/x.py", 10, "other msg")
    assert a.fingerprint == b.fingerprint      # survives edits above it
    assert a.fingerprint != c.fingerprint
    assert len(a.fingerprint) == 12


def test_rc_matrix():
    warn = Finding("RCP001", "warning", "a.py", 1, "w")
    note = Finding("VMEM003", "note", "a.py", 2, "n")
    err = Finding("TRC001", "error", "a.py", 3, "e")
    # clean tree -> 0
    assert Report([], {}, 0.0, 1).rc == 0
    # new warning -> 1; new error -> 1
    assert Report([warn], {}, 0.0, 1).rc == 1
    assert Report([err], {}, 0.0, 1).rc == 1
    # notes never block
    assert Report([note], {}, 0.0, 1).rc == 0
    # everything baselined -> 0, listed as suppressed
    baseline = {warn.fingerprint: {"rule": "RCP001"},
                err.fingerprint: {"rule": "TRC001"}}
    report = Report([warn, err], baseline, 0.0, 1)
    assert report.rc == 0
    assert len(report.suppressed) == 2 and not report.new
    # a stale entry (fixed finding) is reported but does not block
    stale = dict(baseline, deadbeef0000={"rule": "LCK001", "path": "b.py"})
    report = Report([warn, err], stale, 0.0, 1)
    assert report.rc == 0
    assert set(report.stale) == {"deadbeef0000"}


def test_report_json_schema():
    warn = Finding("RCP001", "warning", "a.py", 1, "w", hint="h")
    doc = Report([warn], {}, 0.123, 7).to_dict()
    assert doc["schema_version"] == engine.SCHEMA_VERSION
    assert doc["rc"] == 1
    assert doc["files_scanned"] == 7
    assert doc["counts"] == {"total": 1, "new": 1, "suppressed": 0,
                             "stale_baseline": 0}
    (entry,) = doc["findings"]
    assert entry["rule"] == "RCP001" and entry["severity"] == "warning"
    assert entry["path"] == "a.py" and entry["line"] == 1
    assert entry["hint"] == "h"
    assert entry["fingerprint"] == warn.fingerprint
    assert doc["suppressed"] == [] and doc["stale_baseline"] == []


def test_baseline_add_expire_and_reason_preservation(tmp_path):
    path = str(tmp_path / "baseline.json")
    warn = Finding("RCP001", "warning", "a.py", 1, "w")
    err = Finding("TRC001", "error", "b.py", 2, "e")
    save_baseline(path, [warn, err])
    entries = load_baseline(path)
    assert set(entries) == {warn.fingerprint, err.fingerprint}
    assert entries[warn.fingerprint]["reason"].startswith("TODO")
    # a human writes a reason; re-saving (finding fixed -> expires,
    # finding kept -> reason carried forward) must preserve it
    entries[warn.fingerprint]["reason"] = "deliberate, measured"
    save_baseline(path, [warn], old_entries=entries)
    entries = load_baseline(path)
    assert set(entries) == {warn.fingerprint}          # err expired
    assert entries[warn.fingerprint]["reason"] == "deliberate, measured"
    # missing file is an empty baseline, not an error
    assert load_baseline(str(tmp_path / "nope.json")) == {}


def test_run_lint_end_to_end_rc_cycle(tmp_path):
    pkg = tmp_path / "mesh_tpu"
    pkg.mkdir()
    bad = pkg / "bad.py"
    bad.write_text('import os\n\n'
                   'def f():\n'
                   '    return os.environ.get("MESH_TPU_DEMO")\n')
    baseline = str(tmp_path / "tools" / "meshlint_baseline.json")
    rules = lambda: [KnobRegistryRule()]
    # new error -> rc 1
    report = engine.run_lint(str(tmp_path), rules=rules(),
                             baseline_path=baseline)
    assert report.rc == 1 and _codes(report.new) == ["KNB001"]
    # baseline it -> rc 0, suppressed
    save_baseline(baseline, report.new)
    report = engine.run_lint(str(tmp_path), rules=rules(),
                             baseline_path=baseline)
    assert report.rc == 0 and not report.new and len(report.suppressed) == 1
    # fix the file -> rc 0 with a stale baseline entry
    bad.write_text("def f():\n    return None\n")
    report = engine.run_lint(str(tmp_path), rules=rules(),
                             baseline_path=baseline)
    assert report.rc == 0 and not report.findings and len(report.stale) == 1
    assert "stale baseline entry" in report.render_human()


def test_parse_failure_is_a_finding_not_a_crash(tmp_path):
    pkg = tmp_path / "mesh_tpu"
    pkg.mkdir()
    (pkg / "broken.py").write_text("def f(:\n")
    project, failures = build_project(str(tmp_path))
    assert _codes(failures) == ["PARSE"]
    assert failures[0].severity == "error"
    assert project.by_relpath == {}


def test_all_rules_registry():
    rules = all_rules()
    assert [r.id for r in rules] == ["TRC", "RCP", "VMEM", "LCK", "KNB",
                                     "OBS", "LOK", "PAL", "RES", "LED",
                                     "FLW"]
    assert all_rules()[0] is not rules[0]      # fresh instances each call


# -- TRC fixtures ------------------------------------------------------

def test_trc001_item_in_traced_code():
    findings = _run(TracerLeakRule(), """
        import jax

        @jax.jit
        def f(x):
            return x.item()
        """)
    assert _codes(findings) == ["TRC001"]
    assert findings[0].severity == "error"
    # negative: host-side code may call .item() freely
    assert not _run(TracerLeakRule(), """
        def host(x):
            return x.item()
        """)


def test_trc001_reaches_transitive_helpers_and_tolist():
    findings = _run(TracerLeakRule(), """
        import jax

        def helper(x):
            return x.tolist()

        @jax.jit
        def f(x):
            return helper(x)
        """)
    assert _codes(findings) == ["TRC001"]


def test_trc002_block_until_ready():
    findings = _run(TracerLeakRule(), """
        import jax

        def kernel(x):
            x.block_until_ready()
            return x

        g = jax.jit(kernel)
        """)
    assert _codes(findings) == ["TRC002"]
    assert not _run(TracerLeakRule(), """
        def warmup(x):
            x.block_until_ready()
            return x
        """)


def test_trc003_numpy_materialization():
    findings = _run(TracerLeakRule(), """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)
        """)
    assert _codes(findings) == ["TRC003"]
    # negative: jnp inside traced code is the fix, not a finding
    assert not _run(TracerLeakRule(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.asarray(x)
        """)


def test_trc004_float_on_traced_value():
    findings = _run(TracerLeakRule(), """
        import jax

        @jax.jit
        def f(x):
            return float(x) * 2.0
        """)
    assert _codes(findings) == ["TRC004"]
    # negative 1: static_argnames-declared params are host values
    assert not _run(TracerLeakRule(), """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("eps",))
        def f(x, eps):
            return x * float(eps)
        """)
    # negative 2: shape-derived expressions are static even on tracers
    assert not _run(TracerLeakRule(), """
        import jax

        @jax.jit
        def f(x):
            return x * float(x.shape[0])
        """)
    # negative 3: a transitively-reached builder's bare params are
    # trace-build-time config, not tracers...
    assert not _run(TracerLeakRule(), """
        import jax

        def build(flag):
            return bool(flag)

        @jax.jit
        def f(x):
            build(True)
            return x
        """)
    # ...but provably device-derived expressions still flag anywhere
    findings = _run(TracerLeakRule(), """
        import jax
        import jax.numpy as jnp

        def helper(x):
            return float(jnp.sum(x))

        @jax.jit
        def f(x):
            return helper(x)
        """)
    assert _codes(findings) == ["TRC004"]


# -- RCP fixtures ------------------------------------------------------

def test_rcp001_jit_in_loop():
    findings = _run(RecompileHazardRule(), """
        import jax

        def run(fns, xs):
            out = []
            for fn in fns:
                out.append(jax.jit(fn)(xs))
            return out
        """)
    assert _codes(findings) == ["RCP001"]
    assert not _run(RecompileHazardRule(), """
        import jax

        def run(fn, xs):
            jitted = jax.jit(fn)
            return [jitted(x) for x in xs]
        """)


def test_rcp002_lambda_in_function_body():
    findings = _run(RecompileHazardRule(), """
        import jax

        def make(scale):
            return jax.jit(lambda x: x * scale)
        """)
    assert _codes(findings) == ["RCP002"]
    # negative: a module-level jit(lambda) runs once and is fine
    assert not _run(RecompileHazardRule(), """
        import jax

        double = jax.jit(lambda x: x * 2)
        """)


def test_rcp003_non_literal_static_spec():
    findings = _run(RecompileHazardRule(), """
        import jax

        def make(fn, spec):
            return jax.jit(fn, static_argnums=spec)
        """)
    assert _codes(findings) == ["RCP003"]
    # negatives: literals, and one module-constant indirection
    assert not _run(RecompileHazardRule(), """
        import jax

        _STATIC = (0, 1)

        def make(fn):
            a = jax.jit(fn, static_argnums=(0,))
            b = jax.jit(fn, static_argnames=("tile", "eps"))
            c = jax.jit(fn, static_argnums=_STATIC)
            return a, b, c
        """)


# -- VMEM fixtures -----------------------------------------------------

def test_vmem001_budget_overrun():
    findings = _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl

        def build(kernel, tile=4096):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec((tile, 4096))],
                out_specs=pl.BlockSpec((tile, 4096)),
            )
        """)
    # 2 * 4096*4096*4B = 128 MiB >> 16 MiB
    assert _codes(findings) == ["VMEM001"]
    assert findings[0].severity == "error"
    assert "2 spec(s) priced" in findings[0].message
    # negative: comfortable tiles
    assert not _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl

        def build(kernel, tile=256):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec((tile, 128))],
                out_specs=pl.BlockSpec((tile, 128)),
            )
        """)


def test_vmem001_prices_scratch_dtypes():
    # 2048*2048 f32 scratch = 16 MiB exactly, plus a (8,128) spec -> over
    findings = _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec((8, 128))],
                scratch_shapes=[pltpu.VMEM((2048, 2048), jnp.float32)],
            )
        """)
    assert _codes(findings) == ["VMEM001"]
    # bfloat16 halves it -> fits
    assert not _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec((8, 128))],
                scratch_shapes=[pltpu.VMEM((2048, 2048), jnp.bfloat16)],
            )
        """)


def test_vmem001_leading_dims_multiply():
    # a double-buffered DMA ring: (4, 2048, 1024) f32 = 4 x 8 MiB — the
    # leading (buffer) dim must multiply the per-block footprint
    findings = _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel, n_buffers=4):
            return pl.pallas_call(
                kernel,
                scratch_shapes=[
                    pltpu.VMEM((n_buffers, 2048, 1024), jnp.float32)],
            )
        """)
    assert _codes(findings) == ["VMEM001"]
    assert "32.00 MiB" in findings[0].message
    # two buffers of the same block fit (16 MiB is not > the budget)
    assert not _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel, n_buffers=2):
            return pl.pallas_call(
                kernel,
                scratch_shapes=[
                    pltpu.VMEM((n_buffers, 2048, 1024), jnp.float32)],
            )
        """)


def test_vmem001_prices_sublane_padding():
    # (2, 19, 90112) f32 is ~13.1 MiB unpadded but Mosaic lays the 19
    # sublanes out as 24 -> ~16.5 MiB: over budget only under padded
    # pricing.  The misaligned sublane also gets its VMEM003 note.
    findings = _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel):
            return pl.pallas_call(
                kernel,
                scratch_shapes=[
                    pltpu.VMEM((2, 19, 90112), jnp.float32)],
            )
        """)
    assert _codes(findings) == ["VMEM003", "VMEM001"]
    # an explicitly padded, aligned ring under budget (2*24*81920*4 B
    # = 15 MiB) is clean — the fix VMEM001's hint asks for
    assert not _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel):
            return pl.pallas_call(
                kernel,
                scratch_shapes=[
                    pltpu.VMEM((2, 24, 81920), jnp.float32)],
            )
        """)


def test_vmem_bf16_scratch_priced_at_16_128_tile():
    # the bf16 tile is (16, 128) — two values pack per f32 sublane row —
    # so a 24-row bf16 scratch pads to 32 rows: (2, 24, 135168) bf16 is
    # ~12.4 MiB under f32-style (8, 128) pricing but ~16.5 MiB at the
    # real (16, 128) tile -> over budget, plus the dtype-aware VMEM003
    findings = _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel):
            return pl.pallas_call(
                kernel,
                scratch_shapes=[
                    pltpu.VMEM((2, 24, 135168), jnp.bfloat16)],
            )
        """)
    assert _codes(findings) == ["VMEM003", "VMEM001"]
    assert "multiple of 16" in findings[0].message
    assert "2-byte" in findings[0].message
    # the fixture pair's passing half: the same ring aligned to the
    # bf16 tile (2 * 32 * 131072 * 2 B = 16 MiB exactly) is clean
    assert not _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        import jax.numpy as jnp

        def build(kernel):
            return pl.pallas_call(
                kernel,
                scratch_shapes=[
                    pltpu.VMEM((2, 32, 131072), jnp.bfloat16)],
            )
        """)


def test_vmem002_lane_alignment():
    findings = _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl

        def build(kernel):
            return pl.pallas_call(
                kernel, in_specs=[pl.BlockSpec((8, 96))])
        """)
    assert _codes(findings) == ["VMEM002"]
    # negatives: multiples of 128, and lane == 1 (scalar column) exempt
    assert not _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl

        def build(kernel):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec((8, 256)), pl.BlockSpec((8, 1))])
        """)


def test_vmem003_sublane_alignment_is_a_note():
    findings = _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl

        def build(kernel):
            return pl.pallas_call(
                kernel, in_specs=[pl.BlockSpec((3, 128))])
        """)
    assert _codes(findings) == ["VMEM003"]
    assert findings[0].severity == "note"
    assert not _run(VmemBudgetRule(), """
        import jax.experimental.pallas as pl

        def build(kernel):
            return pl.pallas_call(
                kernel, in_specs=[pl.BlockSpec((16, 128))])
        """)


# -- LCK fixtures ------------------------------------------------------

def test_lck001_mixed_discipline_is_an_error():
    findings = _run(LockDisciplineRule(), """
        import threading

        _LOCK = threading.Lock()
        _CACHE = {}

        def put(k, v):
            with _LOCK:
                _CACHE[k] = v

        def racy(k, v):
            _CACHE[k] = v
        """)
    assert _codes(findings) == ["LCK001"]
    assert findings[0].severity == "error"
    # negative: consistently guarded (incl. a *_locked helper)
    assert not _run(LockDisciplineRule(), """
        import threading

        _LOCK = threading.Lock()
        _CACHE = {}

        def put(k, v):
            with _LOCK:
                _CACHE[k] = v

        def _evict_locked(k):
            _CACHE.pop(k, None)
        """)


def test_lck002_never_guarded_is_a_warning():
    findings = _run(LockDisciplineRule(), """
        import threading

        _LOCK = threading.Lock()
        _ITEMS = []

        def add(x):
            _ITEMS.append(x)
        """)
    assert _codes(findings) == ["LCK002"]
    assert findings[0].severity == "warning"
    # negative 1: no module-level lock -> single-threaded by design
    assert not _run(LockDisciplineRule(), """
        _ITEMS = []

        def add(x):
            _ITEMS.append(x)
        """)
    # negative 2: import-time init precedes all threads
    assert not _run(LockDisciplineRule(), """
        import threading

        _LOCK = threading.Lock()
        _ITEMS = []
        _ITEMS.append("seed")
        """)


# -- KNB fixtures ------------------------------------------------------

def test_knb001_raw_env_reads():
    rule = KnobRegistryRule()
    findings = _run(rule, """
        import os

        _ENV = "MESH_TPU_RECORDER"

        def f():
            a = os.environ.get("MESH_TPU_DEMO")
            b = os.getenv(_ENV)
            c = os.environ["MESH_TPU_CACHE"]
            return a, b, c
        """)
    assert _codes(findings) == ["KNB001"] * 3
    # negatives: writes/pops, non-prefix keys, and the registry itself
    assert not _run(rule, """
        import os

        def f():
            os.environ["MESH_TPU_OBS"] = "1"
            del os.environ["MESH_TPU_OBS"]
            return os.environ.get("HOME")
        """)
    assert not check_source(
        rule,
        'import os\nV = os.environ.get("MESH_TPU_DEMO")\n',
        relpath="mesh_tpu/utils/knobs.py")


def test_knb002_doc_table_coverage(tmp_path):
    pkg = tmp_path / "mesh_tpu" / "utils"
    pkg.mkdir(parents=True)
    (pkg / "knobs.py").write_text(
        "def _declare(name, **kw):\n    pass\n\n"
        '_declare("MESH_TPU_ALPHA")\n'
        '_declare("MESH_TPU_BETA")\n')
    rule = KnobRegistryRule()

    def run():
        project, failures = build_project(str(tmp_path))
        assert not failures
        return list(rule.finalize(project))

    # no doc at all -> one error pointing at the generator
    findings = run()
    assert _codes(findings) == ["KNB002"]
    assert "missing" in findings[0].message
    # doc covering one knob -> the other is flagged at its declaration
    doc = tmp_path / "doc"
    doc.mkdir()
    (doc / "configuration.md").write_text("| `MESH_TPU_ALPHA` | ... |\n")
    findings = run()
    assert _codes(findings) == ["KNB002"]
    assert "MESH_TPU_BETA" in findings[0].message
    assert findings[0].line == 5
    # doc covering both -> clean
    (doc / "configuration.md").write_text(
        "| `MESH_TPU_ALPHA` |\n| `MESH_TPU_BETA` |\n")
    assert not run()


def test_knb003_tuning_writes_outside_actuate():
    rule = KnobRegistryRule()
    findings = _run(rule, """
        from mesh_tpu.utils import tuning

        def sidestep():
            tuning._values["coalesce_window_ms"] = 5.0
            tuning._generation += 1
            tuning.get = lambda name: 99
            del tuning._history
            tuning._emit({"knob": "x"}, 1)
        """)
    assert _codes(findings) == ["KNB003"] * 5
    assert "single write path" in " ".join(
        f.hint or "" for f in findings)
    # import alias still resolves
    findings = _run(rule, """
        import mesh_tpu.utils.tuning as rt

        rt._values.clear
        rt._generation = 0
        """)
    assert _codes(findings) == ["KNB003"]
    # negatives: the audited API is fine, reads are fine, and a file
    # with no tuning import is never scanned
    assert not _run(rule, """
        from mesh_tpu.utils import tuning

        def legit():
            tuning.actuate("coalesce_window_ms", 5.0, reason="test")
            return tuning.get("coalesce_window_ms"), tuning.status()
        """)
    assert not _run(rule, """
        _values = {}

        def unrelated():
            _values["x"] = 1
        """)
    # the write path itself is exempt
    assert not check_source(
        rule,
        "from . import tuning\ntuning._generation = 1\n",
        relpath="mesh_tpu/utils/tuning.py")


# -- OBS fixtures ------------------------------------------------------

def test_obs001_undocumented_series(tmp_path):
    pkg = tmp_path / "mesh_tpu"
    pkg.mkdir()
    (pkg / "instrumented.py").write_text(
        'from mesh_tpu.obs import counter\n\n\n'
        'def hit():\n'
        '    counter("mesh_tpu_fixture_hits_total").inc()\n')
    doc = tmp_path / "doc"
    doc.mkdir()
    rule = ObservabilityHygieneRule()

    def run():
        project, failures = build_project(str(tmp_path))
        assert not failures
        return list(rule.finalize(project))

    (doc / "observability.md").write_text("| `mesh_tpu_other_total` |\n")
    findings = run()
    assert _codes(findings) == ["OBS001"]
    assert findings[0].severity == "error"
    assert "mesh_tpu_fixture_hits_total" in findings[0].message
    assert findings[0].path == "mesh_tpu/instrumented.py"
    # brace shorthand on the doc side documents it -> clean
    (doc / "observability.md").write_text(
        "| `mesh_tpu_fixture_{hits,misses}_total` |\n")
    assert not run()


def test_obs002_dynamic_series_name():
    rule = ObservabilityHygieneRule()
    findings = _run(rule, """
        def record(registry, name):
            registry.counter(name).inc()
        """)
    assert _codes(findings) == ["OBS002"]
    # negatives: a literal name, and the registry implementation itself
    assert not _run(rule, """
        def record(registry):
            registry.counter("mesh_tpu_fixture_total").inc()
        """)
    assert not check_source(
        rule,
        "def record(registry, name):\n"
        "    registry.counter(name).inc()\n",
        relpath="mesh_tpu/obs/metrics.py")


def test_obs003_dynamic_label_names():
    rule = ObservabilityHygieneRule()
    findings = _run(rule, """
        def record(c, labels):
            c.inc(**labels)
        """)
    assert _codes(findings) == ["OBS003"]
    # negatives: named labels (dynamic VALUES are fine), and a **dict
    # literal whose keys are statically visible
    assert not _run(rule, """
        def record(c, tenant):
            c.inc(tenant=tenant)
            c.observe(0.5, **{"tier": "gold"})
        """)


def test_obs004_raw_clock_reads():
    rule = ObservabilityHygieneRule()
    findings = _run(rule, """
        import time

        def f():
            return time.perf_counter()
        """)
    assert _codes(findings) == ["OBS004"]
    # negatives: aliasing without calling (the obs.clock idiom), and
    # the exempt subtrees
    assert not _run(rule, """
        import time

        monotonic = time.perf_counter
        """)
    assert not check_source(
        rule,
        "import time\n\n\ndef f():\n    return time.time()\n",
        relpath="mesh_tpu/obs/clock_impl.py")


def test_obs005_ledger_stage_doc_coverage(tmp_path):
    pkg = tmp_path / "mesh_tpu" / "obs"
    pkg.mkdir(parents=True)
    (pkg / "ledger.py").write_text(
        'LEDGER_STAGES = ("queue", "dispatch")\n')
    doc = tmp_path / "doc"
    doc.mkdir()
    rule = ObservabilityHygieneRule()

    def run():
        project, failures = build_project(str(tmp_path))
        assert not failures
        return list(rule.finalize(project))

    # one stage documented, one missing -> the missing one is flagged
    # at the tuple's assignment line with an error severity
    (doc / "observability.md").write_text("| `queue` | ... |\n")
    findings = run()
    assert _codes(findings) == ["OBS005"]
    assert findings[0].severity == "error"
    assert "dispatch" in findings[0].message
    assert findings[0].path == "mesh_tpu/obs/ledger.py"
    assert findings[0].line == 1
    # an unbackticked mention does NOT count: the doc contract is the
    # literal `stage` form the runbook tells operators to grep for
    (doc / "observability.md").write_text(
        "| `queue` |\nthe dispatch stage\n")
    findings = run()
    assert _codes(findings) == ["OBS005"]
    # both stages backticked -> clean
    (doc / "observability.md").write_text(
        "| `queue` | ... |\n| `dispatch` | ... |\n")
    assert not run()


def test_obs006_unbounded_label_values():
    rule = ObservabilityHygieneRule()
    # every provably-unbounded shape fires: f-string, %-format,
    # str()/.format(), and a per-request identity terminal
    findings = _run(rule, """
        def record(c, h, ctx, digest):
            c.inc(key=f"tenant-{ctx.tenant}")
            c.inc(req="%s" % ctx.seq)
            h.observe(0.5, who=str(ctx.tenant))
            h.observe(0.5, key=digest)
            c.inc(rid=ctx.request_id)
        """)
    assert _codes(findings) == ["OBS006"] * 5
    assert all(f.severity == "error" for f in findings)
    assert "request_id" in findings[4].message
    assert "exemplar" in (findings[0].hint or "")
    # negatives: bounded values (tenant/stage/outcome/replica and
    # session ids are admission-bounded), the sanctioned exemplar=
    # keyword, literals, span.set tagging, and the registry itself
    assert not _run(rule, """
        def record(c, h, sp, ctx, rid):
            c.inc(tenant=ctx.tenant, stage="plan", replica=ctx.replica)
            c.inc(tenant=self.session_id)
            h.observe(0.5, exemplar=rid)
            h.observe(0.5, tier="gold")
            sp.set(request_id=rid)
        """)
    assert not check_source(
        rule,
        "def f(c, d):\n    c.inc(digest=d)\n",
        relpath="mesh_tpu/obs/metrics.py")


# -- LOK fixtures (interprocedural lock order) -------------------------

def test_lok001_cross_function_lock_order_cycle():
    findings = _run(LockOrderRule(), """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with B:
                with A:
                    pass
        """)
    assert _codes(findings) == ["LOK001"]
    assert findings[0].severity == "error"


def test_lok001_nonreentrant_self_acquire_through_call():
    findings = _run(LockOrderRule(), """
        import threading

        L = threading.Lock()

        def f():
            with L:
                g()

        def g():
            with L:
                pass
        """)
    assert _codes(findings) == ["LOK001"]
    # the same shape on an RLock is legal re-entrancy
    assert not _run(LockOrderRule(), """
        import threading

        L = threading.RLock()

        def f():
            with L:
                g()

        def g():
            with L:
                pass
        """)


def test_lok001_consistent_order_is_clean():
    assert not _run(LockOrderRule(), """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with A:
                with B:
                    pass
        """)


def test_lok002_blocking_call_under_lock():
    findings = _run(LockOrderRule(), """
        import threading

        L = threading.Lock()

        def f(path):
            with L:
                with open(path) as fh:
                    return fh.read()
        """)
    assert _codes(findings) == ["LOK002"]
    assert findings[0].severity == "warning"
    assert "open" in findings[0].message


def test_lok002_blocking_reached_through_call_chain():
    findings = _run(LockOrderRule(), """
        import threading
        import subprocess

        L = threading.Lock()

        def helper(cmd):
            return middle(cmd)

        def middle(cmd):
            return subprocess.run(cmd)

        def f(cmd):
            with L:
                return helper(cmd)
        """)
    assert _codes(findings) == ["LOK002"]
    assert "subprocess.run" in findings[0].message


def test_lok002_blocking_outside_lock_is_clean():
    assert not _run(LockOrderRule(), """
        import threading

        L = threading.Lock()

        def f(path):
            with L:
                n = 1
            with open(path) as fh:
                return fh.read(n)
        """)


def _lok_project(tmp_path, doc_text, a_body, b_body=None):
    """A two-subsystem project + doc/concurrency.md, linted LOK-only."""
    (tmp_path / "mesh_tpu" / "store").mkdir(parents=True)
    (tmp_path / "mesh_tpu" / "obs").mkdir(parents=True)
    (tmp_path / "doc").mkdir()
    (tmp_path / "doc" / "concurrency.md").write_text(doc_text)
    (tmp_path / "mesh_tpu" / "store" / "a.py").write_text(
        textwrap.dedent(a_body))
    (tmp_path / "mesh_tpu" / "obs" / "b.py").write_text(
        textwrap.dedent(b_body or """\
            import threading

            B_LOCK = threading.Lock()
            """))
    report = engine.run_lint(str(tmp_path), rules=[LockOrderRule()],
                             use_baseline=False)
    return report.findings


_LOK_CROSS_MODULE = """\
    import threading

    from mesh_tpu.obs.b import B_LOCK

    A_LOCK = threading.Lock()

    def f():
        with A_LOCK:
            with B_LOCK:
                pass
    """


def test_lok003_edge_contradicting_declared_order(tmp_path):
    findings = _lok_project(tmp_path, textwrap.dedent("""\
        # Canonical lock order
        1. `mesh_tpu/obs/b.py:B_LOCK`
        2. `mesh_tpu/store/a.py:A_LOCK`
        """), _LOK_CROSS_MODULE)
    assert _codes(findings) == ["LOK003"]
    assert findings[0].severity == "error"


def test_lok004_undeclared_cross_subsystem_edge(tmp_path):
    findings = _lok_project(tmp_path, textwrap.dedent("""\
        # Canonical lock order
        1. `mesh_tpu/other/c.py:C_LOCK`
        """), _LOK_CROSS_MODULE)
    assert _codes(findings) == ["LOK004"]


def test_lok_declared_order_matching_code_is_clean(tmp_path):
    assert not _lok_project(tmp_path, textwrap.dedent("""\
        # Canonical lock order
        1. `mesh_tpu/store/a.py:A_LOCK`
        2. `mesh_tpu/obs/b.py:B_LOCK`
        """), _LOK_CROSS_MODULE)


def test_lok005_stale_doc_entry(tmp_path):
    findings = _lok_project(tmp_path, textwrap.dedent("""\
        # Canonical lock order
        1. `mesh_tpu/store/a.py:A_LOCK`
        2. `mesh_tpu/store/a.py:GONE_LOCK`
        """), """\
        import threading

        A_LOCK = threading.Lock()
        """)
    assert _codes(findings) == ["LOK005"]
    assert "GONE_LOCK" in findings[0].message


def test_lok002_allowlist_is_site_scoped(tmp_path):
    blocking = """\
        import threading

        A_LOCK = threading.Lock()

        def writer(path):
            with A_LOCK:
                with open(path, "w") as fh:
                    fh.write("x")

        def other(path):
            with A_LOCK:
                with open(path) as fh:
                    return fh.read()
        """
    doc = textwrap.dedent("""\
        # Canonical lock order
        1. `mesh_tpu/store/a.py:A_LOCK`

        # Blocking-under-lock allowlist
        | `mesh_tpu/store/a.py:A_LOCK` | `open` | `writer` | reason |
        """)
    findings = _lok_project(tmp_path, doc, blocking)
    # `writer` is allowlisted by site; `other` still fires
    assert _codes(findings) == ["LOK002"]
    assert "other" in findings[0].message


def test_parse_concurrency_doc():
    order, allow = parse_concurrency_doc(textwrap.dedent("""\
        # Canonical lock order
        prose with `not/a/lock` tokens
        1. `mesh_tpu/a.py:X` first
        2. `mesh_tpu/b.py:Y.z`

        # Blocking-under-lock allowlist
        | `mesh_tpu/a.py:X` | `open` | `f.g` | why |
        | `mesh_tpu/b.py:Y.z` | `*` | because |
        """))
    assert order == {"mesh_tpu/a.py:X": 0, "mesh_tpu/b.py:Y.z": 1}
    assert ("mesh_tpu/a.py:X", "open", "f.g") in allow
    assert ("mesh_tpu/b.py:Y.z", "*", "*") in allow
    assert parse_concurrency_doc(None) == ({}, set())


# -- PAL fixtures (Pallas DMA/semaphore discipline) --------------------

def test_pal001_start_without_wait():
    findings = _run(PallasDmaRule(), """
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_hbm, o_ref, buf, sem):
            pltpu.make_async_copy(
                x_hbm.at[0], buf.at[0], sem.at[0]).start()
            o_ref[:] = buf[0]
        """)
    assert _codes(findings) == ["PAL001"]
    assert findings[0].severity == "error"


def test_pal001_paired_start_wait_is_clean():
    assert not _run(PallasDmaRule(), """
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_hbm, o_ref, buf, sem):
            def dma(slot):
                return pltpu.make_async_copy(
                    x_hbm.at[slot], buf.at[slot], sem.at[slot])
            dma(0).start()
            dma(0).wait()
            o_ref[:] = buf[0]
        """)


def test_pal002_ring_slot_aliasing():
    findings = _run(PallasDmaRule(), """
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_hbm, o_ref, buf, sem):
            def dma(slot):
                return pltpu.make_async_copy(
                    x_hbm.at[slot], buf.at[slot], sem.at[slot])
            dma(0).start()
            dma(1).start()
            dma(0).wait()
            o_ref[:] = buf[1]
        """)
    assert _codes(findings) == ["PAL002"]
    assert findings[0].severity == "error"


def test_pal003_any_operand_touched_by_compute():
    src = """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_any, o_ref):
            o_ref[:] = x_any[0]

        def run(x):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            )(x)
        """
    findings = _run(PallasDmaRule(), src)
    assert _codes(findings) == ["PAL003"]
    assert findings[0].severity == "error"


def test_pal003_any_operand_via_dma_is_clean():
    assert not _run(PallasDmaRule(), """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_any, o_ref, buf, sem):
            copy = pltpu.make_async_copy(x_any.at[0], buf.at[0], sem)
            copy.start()
            copy.wait()
            o_ref[:] = buf[0]

        def run(x):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                scratch_shapes=[
                    pltpu.VMEM((2, 8, 128), jnp.float32),
                    pltpu.SemaphoreType.DMA((2,)),
                ],
            )(x)
        """)


def test_pal004_loop_body_start_wait_imbalance():
    findings = _run(PallasDmaRule(), """
        import jax
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_hbm, o_ref, buf, sem):
            def dma(slot):
                return pltpu.make_async_copy(
                    x_hbm.at[slot], buf.at[slot], sem.at[slot])
            def body(i, c):
                dma(i).start()
                dma(i + 1).start()
                dma(i).wait()
                return c
            jax.lax.fori_loop(0, 4, body, 0)
            dma(0).wait()
            o_ref[:] = buf[0]
        """)
    assert _codes(findings) == ["PAL004"]
    assert findings[0].severity == "warning"


def test_pal005_ring_and_semaphore_slot_counts_disagree():
    findings = _run(PallasDmaRule(), """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_any, o_ref, buf, sem):
            pltpu.make_async_copy(x_any.at[0], buf.at[0], sem.at[0]).start()
            pltpu.make_async_copy(x_any.at[0], buf.at[0], sem.at[0]).wait()
            o_ref[:] = buf[0]

        def run(x):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                scratch_shapes=[
                    pltpu.VMEM((2, 8, 128), jnp.float32),
                    pltpu.SemaphoreType.DMA((3,)),
                ],
            )(x)
        """)
    assert _codes(findings) == ["PAL005"]
    assert findings[0].severity == "error"
    assert "2 slot(s)" in findings[0].message and "3" in findings[0].message


def test_pal005_kernel_arity_mismatch():
    findings = _run(PallasDmaRule(), """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(a_ref, o_ref):
            o_ref[:] = a_ref[:]

        def run(x, y):
            return pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0)),
                          pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            )(x, y)
        """)
    assert _codes(findings) == ["PAL005"]
    assert "takes 2 ref(s)" in findings[0].message


def test_pal_shipped_stream_kernel_is_clean():
    report = engine.run_lint(
        _REPO, rules=[PallasDmaRule()],
        paths=[os.path.join(_REPO, "mesh_tpu", "accel",
                            "pallas_stream.py")],
        use_baseline=False)
    assert report.rc == 0, [f.message for f in report.findings]


# -- RES: path-sensitive resource pairing ------------------------------

def test_res001_lock_leaks_on_early_return():
    findings = _run(ResourcePathRule(), """
        def dispatch(self, flag):
            self.lock.acquire()
            if flag:
                return early()
            self.lock.release()
    """)
    assert _codes(findings) == ["RES001"]
    (f,) = findings
    assert "lock 'self.lock'" in f.message
    # the CFG path witness rides along for SARIF codeFlows
    assert f.witness and all(isinstance(line, int)
                             for line, _ in f.witness)


def test_res_lock_released_in_finally_is_clean():
    findings = _run(ResourcePathRule(), """
        def dispatch(self, flag):
            self.lock.acquire()
            try:
                if flag:
                    return early()
                work(self)
            finally:
                self.lock.release()
    """)
    assert findings == []


def test_res002_exception_escapes_between_acquire_and_release():
    findings = _run(ResourcePathRule(), """
        def dispatch(self):
            self.lock.acquire()
            handle(self)
            self.lock.release()
    """)
    assert _codes(findings) == ["RES002"]
    assert "finally" in findings[0].hint


def test_res001_ledger_record_skipped_by_early_return():
    findings = _run(ResourcePathRule(), """
        def serve(ledger, req):
            rec = ledger.open(req)
            if req.bad:
                return None
            work(req)
            ledger.close(rec, outcome="ok")
    """)
    assert _codes(findings) == ["RES001"]
    assert "ledger record 'rec'" in findings[0].message


def test_res_ledger_record_that_escapes_is_not_tracked():
    # storing the record hands off ownership — someone else closes it
    findings = _run(ResourcePathRule(), """
        def serve(self, ledger, req):
            rec = ledger.open(req)
            if req.bad:
                return None
            self.pending[req.name] = rec
    """)
    assert findings == []


def test_res001_manual_cm_enter_without_exit_on_branch():
    findings = _run(ResourcePathRule(), """
        def attach(self, flag):
            ctx = self.span.__enter__()
            if flag:
                return ctx
            self.span.__exit__(None, None, None)
    """)
    assert _codes(findings) == ["RES001"]
    assert "context manager 'self.span'" in findings[0].message


def test_res_cm_delegation_idiom_is_not_tracked():
    # an __enter__ method entering a cm stored on self: the paired
    # __exit__ lives in the sibling method, outside this CFG
    findings = _run(ResourcePathRule(), """
        class StreamSpan:
            def __enter__(self):
                self._inner.__enter__()
                return self

            def __exit__(self, exc_type, exc, tb):
                self._inner.__exit__(exc_type, exc, tb)
    """)
    assert findings == []


def test_res003_dma_wait_skipped_on_a_branch():
    findings = _run(ResourcePathRule(), """
        def body(i, ref):
            copy = pltpu.make_async_copy(src, dst, sem)
            copy.start()
            if i == 0:
                copy.wait()
            return ref

        def kernel(ref):
            jax.lax.fori_loop(0, 8, body, ref)
    """)
    assert _codes(findings) == ["RES003"]
    assert "unbalanced on some path" in findings[0].message


def test_res003_balanced_loop_body_is_clean():
    findings = _run(ResourcePathRule(), """
        def body(i, ref):
            copy = pltpu.make_async_copy(src, dst, sem)
            copy.start()
            copy.wait()
            return ref

        def kernel(ref):
            jax.lax.fori_loop(0, 8, body, ref)
    """)
    assert findings == []


# -- LED: request-lifecycle ledger completeness ------------------------

def test_led001_completion_path_with_no_close():
    findings = _run(LedgerLifecycleRule(), """
        class Service:
            def admit(self, req):
                req.record = self.ledger.open(req.name)
                return req

            def stop(self, queue):
                for req in queue:
                    req.future.cancel()
    """)
    assert _codes(findings) == ["LED001"]
    (f,) = findings
    assert "no ledger close" in f.message
    assert f.witness


def test_led_guarded_close_on_every_completion_path_is_clean():
    findings = _run(LedgerLifecycleRule(), """
        class Service:
            def admit(self, req):
                req.record = self.ledger.open(req.name)
                return req

            def stop(self, queue):
                for req in queue:
                    req.future.cancel()
                    if req.record is not None:
                        self.ledger.close(req.record,
                                          outcome="cancelled")
    """)
    assert findings == []


def test_led002_undocumented_outcome_label():
    # the label is a variable: reaching definitions resolve it
    findings = _run(LedgerLifecycleRule(), """
        def finish(ledger, rec, ok):
            label = "ok"
            if not ok:
                label = "oops"
            ledger.close(rec, outcome=label)
    """)
    assert _codes(findings) == ["LED002"]
    assert "'oops'" in findings[0].message


def test_led002_documented_conditional_label_is_clean():
    findings = _run(LedgerLifecycleRule(), """
        def finish(ledger, rec, ok):
            ledger.close(rec, outcome="ok" if ok else "error")
    """)
    assert findings == []


def test_led004_double_close_on_one_path():
    findings = _run(LedgerLifecycleRule(), """
        def teardown(ledger, rec):
            ledger.close(rec, outcome="ok")
            note(rec.name)
            ledger.close(rec, outcome="ok")
    """)
    assert "LED004" in _codes(findings)


def test_led004_mutually_exclusive_closes_are_clean():
    findings = _run(LedgerLifecycleRule(), """
        def teardown(ledger, rec, ok):
            if ok:
                ledger.close(rec, outcome="ok")
            else:
                ledger.close(rec, outcome="error")
    """)
    assert findings == []


# -- FLW: flow-sensitive TRC/RCP upgrades ------------------------------

def test_flw001_device_derived_local_crosses_to_host():
    findings = _run(FlowSensitiveRule(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            y = jnp.sum(x)
            return float(y)
    """)
    assert _codes(findings) == ["FLW001"]
    assert "'y'" in findings[0].message


def test_flw001_host_rebind_kills_the_device_definition():
    findings = _run(FlowSensitiveRule(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            y = jnp.sum(x)
            y = x.shape[0]
            return float(y)
    """)
    assert findings == []


def test_flw002_per_iteration_item_on_jitted_result():
    findings = _run(FlowSensitiveRule(), """
        import jax

        @jax.jit
        def update(params, batch):
            return params

        def train(data, params):
            losses = []
            for batch in data:
                loss = update(params, batch)
                losses.append(loss.item())
            return losses
    """)
    assert _codes(findings) == ["FLW002"]
    assert "once per iteration" in findings[0].message


def test_flw002_single_sync_after_the_loop_is_clean():
    findings = _run(FlowSensitiveRule(), """
        import jax

        @jax.jit
        def update(params, batch):
            return params

        def train(data, params):
            total = 0.0
            for batch in data:
                total = update(params, batch)
            return total.item()
    """)
    assert findings == []


def test_trc004_suppressed_when_param_rebound_to_host_on_all_paths():
    # the measured false-positive class FLW removes: a traced parameter
    # rebound to a proven host value before the conversion
    quiet = _run(TracerLeakRule(), """
        import jax

        @jax.jit
        def step(x):
            x = x.shape[0]
            return float(x)
    """)
    assert "TRC004" not in _codes(quiet)
    # ...but a conditional rebind leaves the traced binding reachable
    loud = _run(TracerLeakRule(), """
        import jax

        @jax.jit
        def step(x, flag):
            if flag:
                x = x.shape[0]
            return float(x)
    """)
    assert "TRC004" in _codes(loud)


def test_rcp001_suppressed_under_build_once_guards():
    quiet_none = _run(RecompileHazardRule(), """
        import jax

        def serve(reqs):
            f = None
            for r in reqs:
                if f is None:
                    f = jax.jit(model)
                f(r)
    """)
    assert "RCP001" not in _codes(quiet_none)
    quiet_memo = _run(RecompileHazardRule(), """
        import jax

        def serve(reqs, cache):
            for r in reqs:
                if r.key not in cache:
                    cache[r.key] = jax.jit(model)
                cache[r.key](r)
    """)
    assert "RCP001" not in _codes(quiet_memo)
    loud = _run(RecompileHazardRule(), """
        import jax

        def serve(reqs):
            for r in reqs:
                f = jax.jit(model)
                f(r)
    """)
    assert "RCP001" in _codes(loud)


# -- SARIF output ------------------------------------------------------

def test_sarif_output_shape():
    new = Finding("LOK001", "error", "mesh_tpu/a.py", 3, "cycle",
                  hint="break it")
    kept = Finding("VMEM002", "warning", "mesh_tpu/b.py", 7, "lane")
    doc = Report([new, kept],
                 {kept.fingerprint: {"reason": "deliberate xyz block"}},
                 0.1, 2).to_sarif()
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "meshlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == {"LOK001", "VMEM002"}
    by_rule = {r["ruleId"]: r for r in run["results"]}
    assert by_rule["LOK001"]["level"] == "error"
    assert "suppressions" not in by_rule["LOK001"]
    assert by_rule["VMEM002"]["suppressions"][0]["justification"] \
        == "deliberate xyz block"
    loc = by_rule["LOK001"]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "mesh_tpu/a.py"
    assert loc["region"]["startLine"] == 3
    assert by_rule["LOK001"]["partialFingerprints"]["meshlint/v1"] \
        == new.fingerprint


def test_witness_rides_json_human_and_sarif_codeflows():
    f = Finding("RES001", "error", "mesh_tpu/a.py", 4, "leak",
                witness=[(4, "opens here"),
                         (6, "if takes the false branch"),
                         (9, None)])
    plain = Finding("VMEM002", "warning", "mesh_tpu/b.py", 7, "lane")
    report = Report([f, plain], {}, 0.1, 2)
    # JSON: the witness array, notes preserved
    by_rule = {e["rule"]: e for e in report.to_dict()["findings"]}
    assert by_rule["RES001"]["witness"] == [
        {"line": 4, "note": "opens here"},
        {"line": 6, "note": "if takes the false branch"},
        {"line": 9, "note": None}]
    assert "witness" not in by_rule["VMEM002"]
    # human: indented "path:" steps under the finding
    human = report.render_human()
    assert "path: L6 — if takes the false branch" in human
    # SARIF: one codeFlow whose threadFlow walks the same lines
    results = {r["ruleId"]: r for r in
               report.to_sarif()["runs"][0]["results"]}
    (flow,) = results["RES001"]["codeFlows"]
    locs = flow["threadFlows"][0]["locations"]
    assert [l["location"]["physicalLocation"]["region"]["startLine"]
            for l in locs] == [4, 6, 9]
    texts = [l["location"]["message"]["text"] for l in locs]
    assert texts[0] == "opens here"
    assert all(texts), "every step needs non-empty message text"
    assert "codeFlows" not in results["VMEM002"]


def test_cli_sarif_and_changed(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "lint", "--format",
         "sarif"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(proc.stdout)
    assert sarif["runs"][0]["tool"]["driver"]["name"] == "meshlint"
    # --changed: clean checkout -> "no changed files"; dirty tree ->
    # a fast partial lint.  Either way the shipped tree must pass.
    proc = subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "lint", "--changed"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_cli_profile_flag():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    target = os.path.join("mesh_tpu", "obs", "ledger.py")
    proc = subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "lint", "--profile",
         target],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "meshlint profile" in out
    for token in ("parse", "cfg", "dataflow", "rules"):
        assert token in out, token
    # machine formats keep stdout parseable: the table moves to stderr
    # and --json embeds the same numbers structurally
    proc = subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "lint", "--profile",
         "--json", target],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert "rules_s" in doc["profile"]
    assert "meshlint profile" in proc.stderr


# -- the shipped tree (the gate-0 contract) ----------------------------

def test_shipped_tree_lints_clean_and_fast():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "lint", "--json"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["schema_version"] == engine.SCHEMA_VERSION
    assert doc["rc"] == 0
    assert doc["counts"]["new"] == 0
    assert doc["files_scanned"] > 50
    # the gate-0 budget: chip-free and fast enough to run before
    # every chip cycle, CFGs and the interprocedural graph included.
    # Best of two runs: the budget is about the linter, not about a
    # transient load spike on a shared test machine.
    elapsed = doc["elapsed_s"]
    if elapsed >= 3.0:
        proc = subprocess.run(
            [sys.executable, "-m", "mesh_tpu.cli", "lint", "--json"],
            cwd=_REPO, env=env, capture_output=True, text=True,
            timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        elapsed = min(elapsed,
                      json.loads(proc.stdout)["elapsed_s"])
    assert elapsed < 3.0
    # every baselined suppression must carry a human-written reason
    baseline = load_baseline(engine.default_baseline_path(_REPO))
    assert baseline, "shipped baseline should not be empty"
    for fingerprint, entry in baseline.items():
        reason = entry.get("reason") or ""
        assert reason and not reason.startswith("TODO"), (
            "baseline entry %s lacks a justification" % fingerprint)
