"""Serialization tests: PLY/OBJ round trips, golden byte-format parity with
the reference's rply-written fixtures (tests/test_mesh.py:35-87 style),
error paths."""

import os

import numpy as np
import pytest

from mesh_tpu import Mesh
from mesh_tpu.errors import MeshError, SerializationError
from mesh_tpu.serialization import read_ply, write_ply_data

from . import has_reference_data, reference_data_folder, temporary_files_folder
from .fixtures import box


class TestPly:
    def _roundtrip(self, tmp_path, **kw):
        v, f = box()
        src = Mesh(v=v, f=f)
        path = str(tmp_path / "out.ply")
        src.write_ply(path, **kw)
        dst = Mesh(filename=path)
        np.testing.assert_allclose(dst.v, v, atol=1e-6)  # f32 storage
        np.testing.assert_array_equal(dst.f, f)
        return dst

    def test_ascii_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, ascii=True)

    def test_little_endian_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, little_endian=True)

    def test_big_endian_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, little_endian=False)

    def test_colors_and_normals_roundtrip(self, tmp_path):
        v, f = box()
        m = Mesh(v=v, f=f)
        m.set_vertex_colors("red")
        m.vn = np.tile([0.0, 0.0, 1.0], (8, 1))
        path = str(tmp_path / "cn.ply")
        m.write_ply(path)
        back = Mesh(filename=path)
        np.testing.assert_allclose(back.vc, m.vc, atol=1 / 255.0 + 1e-6)
        np.testing.assert_allclose(back.vn, m.vn, atol=1e-6)

    def test_flip_faces(self, tmp_path):
        v, f = box()
        m = Mesh(v=v, f=f)
        path = str(tmp_path / "flip.ply")
        m.write_ply(path, flip_faces=True)
        back = Mesh(filename=path)
        np.testing.assert_array_equal(back.f, f[:, ::-1])

    def test_comments(self, tmp_path):
        v, f = box()
        path = str(tmp_path / "c.ply")
        Mesh(v=v, f=f).write_ply(path, ascii=True, comments=["hello\nworld"])
        text = open(path).read()
        assert "comment hello\ncomment world" in text

    def test_missing_file_raises(self):
        with pytest.raises(SerializationError, match="Failed to open PLY file"):
            Mesh(filename=os.path.join(temporary_files_folder, "nope.ply"))

    def test_error_hierarchy(self):
        """reference tests/test_mesh.py:49-60."""
        assert issubclass(SerializationError, MeshError)


@pytest.mark.skipif(not has_reference_data(), reason="reference data not mounted")
class TestGoldenParity:
    def test_load_reference_box_obj(self):
        m = Mesh(filename=os.path.join(reference_data_folder, "test_box.obj"))
        assert m.v.shape == (8, 3)
        assert m.f.shape == (12, 3)
        assert set(m.segm.keys()) == {"a", "b", "c"}

    def test_load_reference_box_ply_ascii_and_binary(self):
        ma = Mesh(filename=os.path.join(reference_data_folder, "test_box.ply"))
        mb = Mesh(filename=os.path.join(reference_data_folder, "test_box_le.ply"))
        np.testing.assert_allclose(ma.v, mb.v, atol=1e-7)
        np.testing.assert_array_equal(ma.f, mb.f)
        assert ma.v.shape == (8, 3)

    def test_write_ascii_bytematch(self, tmp_path):
        """Our writer reproduces rply's ascii bytes exactly
        (reference golden-equality style, tests/test_mesh.py:67-87)."""
        golden = os.path.join(reference_data_folder, "test_box.ply")
        m = Mesh(filename=golden)
        out = str(tmp_path / "rewrite.ply")
        m.write_ply(out, ascii=True)
        assert open(out, "rb").read() == open(golden, "rb").read()

    def test_write_binary_bytematch(self, tmp_path):
        golden = os.path.join(reference_data_folder, "test_box_le.ply")
        m = Mesh(filename=golden)
        out = str(tmp_path / "rewrite_le.ply")
        m.write_ply(out, little_endian=True)
        assert open(out, "rb").read() == open(golden, "rb").read()

    def test_landmarks_pp(self):
        m = Mesh(
            filename=os.path.join(reference_data_folder, "test_box.obj"),
            ppfilename=os.path.join(reference_data_folder, "test_box.pp"),
        )
        assert len(m.landm) > 0
        assert set(m.landm) == set(m.landm_regressors)


class TestObj:
    def test_roundtrip(self, tmp_path):
        v, f = box()
        m = Mesh(v=v, f=f)
        path = str(tmp_path / "out.obj")
        m.write_obj(path)
        back = Mesh(filename=path)
        np.testing.assert_allclose(back.v, v, atol=1e-6)
        np.testing.assert_array_equal(back.f, f)

    def test_segments_roundtrip(self, tmp_path):
        v, f = box()
        m = Mesh(v=v, f=f, segm={"top": [2, 3], "bottom": [0, 1]})
        path = str(tmp_path / "seg.obj")
        m.write_obj(path)
        back = Mesh(filename=path)
        assert set(back.segm) == {"top", "bottom"}
        assert len(back.segm["top"]) == 2

    def test_landmark_comment(self, tmp_path):
        path = str(tmp_path / "landm.obj")
        with open(path, "w") as fp:
            fp.write("#landmark nose\nv 0 0 0\nv 1 0 0\nv 0 1 0\nf 1 2 3\n")
        m = Mesh(filename=path)
        assert m.landm == {"nose": 0}

    def test_polygon_fan_triangulation(self, tmp_path):
        path = str(tmp_path / "quad.obj")
        with open(path, "w") as fp:
            fp.write("v 0 0 0\nv 1 0 0\nv 1 1 0\nv 0 1 0\nf 1 2 3 4\n")
        m = Mesh(filename=path)
        np.testing.assert_array_equal(m.f, [[0, 1, 2], [0, 2, 3]])

    def test_json(self, tmp_path):
        import json

        v, f = box()
        path = str(tmp_path / "m.json")
        Mesh(v=v, f=f, basename="box").write_json(path)
        data = json.load(open(path))
        assert data["name"] == "box"
        assert len(data["vertices"]) == 8
        assert len(data["faces"]) == 12

    def test_json_roundtrip(self, tmp_path):
        """JSON is write-only in the reference; here Mesh(filename=...)
        reads write_json output back."""
        v, f = box()
        path = str(tmp_path / "m.json")
        Mesh(v=v, f=f, basename="box").write_json(path)
        m = Mesh(filename=path)
        np.testing.assert_allclose(m.v, v)
        np.testing.assert_array_equal(m.f, f)
        assert m.f.dtype == np.uint32 and m.v.dtype == np.float64
        assert m.basename == "box"

    def test_json_loader_errors(self, tmp_path):
        from mesh_tpu.errors import SerializationError

        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        with pytest.raises(SerializationError, match="Failed to load"):
            Mesh(filename=str(bad))
        empty = tmp_path / "empty.json"
        empty.write_text("{}")
        with pytest.raises(SerializationError, match="no 'vertices'"):
            Mesh(filename=str(empty))
        scalar = tmp_path / "scalar.json"
        scalar.write_text("42")
        with pytest.raises(SerializationError, match="no 'vertices'"):
            Mesh(filename=str(scalar))
        ragged = tmp_path / "ragged.json"
        ragged.write_text('{"vertices": [[0, 0], [1, 1, 1]]}')
        with pytest.raises(SerializationError, match="Malformed"):
            Mesh(filename=str(ragged))
        wide = tmp_path / "wide.json"
        wide.write_text('{"vertices": [[0, 0, 0, 0], [1, 1, 1, 1], [2, 2, 2, 2]]}')
        with pytest.raises(SerializationError, match="3 entries"):
            Mesh(filename=str(wide))
        nonlist = tmp_path / "nonlist.json"
        nonlist.write_text('{"vertices": 5}')
        with pytest.raises(SerializationError, match="list of xyz"):
            Mesh(filename=str(nonlist))
        badface = tmp_path / "badface.json"
        badface.write_text(
            '{"vertices": [[0,0,0],[1,0,0],[0,1,0]], "faces": [[0,1,7]]}'
        )
        with pytest.raises(SerializationError, match="out of range"):
            Mesh(filename=str(badface))

    def test_three_json_not_loadable(self, tmp_path):
        v, f = box()
        m = Mesh(v=v, f=f)
        m.vt = np.zeros((8, 2))
        m.ft = np.asarray(f).copy()
        m.vn = m.estimate_vertex_normals()
        m.fn = np.asarray(f).copy()
        path = str(tmp_path / "three.json")
        m.write_three_json(path)
        from mesh_tpu.errors import SerializationError

        with pytest.raises(SerializationError, match="three.js"):
            Mesh(filename=path)

    def test_three_json(self, tmp_path):
        """three.js model v3.1 layout (reference serialization.py:232-280):
        flat vertex floats, type-42 face records of v/uv/normal indices."""
        import json

        v, f = box()
        m = Mesh(v=v, f=f)
        m.vt = np.zeros((8, 2))
        m.ft = np.asarray(f).copy()
        m.vn = m.estimate_vertex_normals()
        m.fn = np.asarray(f).copy()
        path = str(tmp_path / "m.js")
        m.write_three_json(path, name="boxy")
        data = json.load(open(path))
        assert data["metadata"]["formatVersion"] == 3.1
        assert data["metadata"]["vertices"] == 8
        assert data["metadata"]["faces"] == 12
        assert len(data["vertices"]) == 24          # 8 * xyz
        # each 11-int record: [42, v0 v1 v2, material, t0 t1 t2, n0 n1 n2]
        faces = np.array(data["faces"]).reshape(12, 11)
        assert (faces[:, 0] == 42).all()
        np.testing.assert_array_equal(faces[:, 1:4], np.asarray(f))
        assert len(data["materials"]) == 1

    def test_write_mtl(self, tmp_path):
        v, f = box()
        m = Mesh(v=v, f=f)
        path = str(tmp_path / "m.mtl")
        m.write_mtl(path, "mat0", "tex.png")
        body = open(path).read()
        assert "newmtl mat0" in body
        assert "map_Kd tex.png" in body


class TestPlyBigEndianIntCounts:
    def test_int_list_count_big_endian(self, tmp_path):
        """List-count fields must honor the file's byte order (a BE file with
        'property list int int' counts reads n=3, not 0x03000000)."""
        import struct

        from mesh_tpu.serialization.ply import read_ply

        path = str(tmp_path / "be_int.ply")
        header = "\n".join([
            "ply", "format binary_big_endian 1.0",
            "element vertex 3",
            "property float x", "property float y", "property float z",
            "element face 1",
            "property list int int vertex_indices",
            "end_header",
        ]) + "\n"
        with open(path, "wb") as fp:
            fp.write(header.encode())
            for xyz in ([0, 0, 0], [1, 0, 0], [0, 1, 0]):
                fp.write(struct.pack(">3f", *xyz))
            fp.write(struct.pack(">i", 3))
            fp.write(struct.pack(">3i", 0, 1, 2))
        res = read_ply(path)
        np.testing.assert_array_equal(res["tri"], [[0, 1, 2]])
        assert res["pts"].shape == (3, 3)


class TestPlyMultiPropertyFaceElement:
    """Face elements with sibling properties next to the index list must not
    misalign the parse (exporters add e.g. per-face flags or texcoords)."""

    def _check(self, res):
        np.testing.assert_array_equal(res["tri"], [[0, 1, 2], [0, 2, 3]])
        assert res["pts"].shape == (4, 3)

    def test_binary_scalar_after_list(self, tmp_path):
        import struct

        from mesh_tpu.serialization.ply import read_ply

        path = str(tmp_path / "multi.ply")
        header = "\n".join([
            "ply", "format binary_little_endian 1.0",
            "element vertex 4",
            "property float x", "property float y", "property float z",
            "element face 2",
            "property list uchar int vertex_indices",
            "property uchar flags",
            "end_header",
        ]) + "\n"
        with open(path, "wb") as fp:
            fp.write(header.encode())
            for xyz in ([0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0]):
                fp.write(struct.pack("<3f", *xyz))
            for idx in ([0, 1, 2], [0, 2, 3]):
                fp.write(struct.pack("<B3i", 3, *idx))
                fp.write(struct.pack("<B", 7))  # flags byte
        self._check(read_ply(path))

    def test_ascii_second_list_ignored(self, tmp_path):
        from mesh_tpu.serialization.ply import read_ply
        from mesh_tpu.serialization import native

        path = str(tmp_path / "twolist.ply")
        with open(path, "w") as fp:
            fp.write("\n".join([
                "ply", "format ascii 1.0",
                "element vertex 4",
                "property float x", "property float y", "property float z",
                "element face 2",
                "property list uchar int vertex_indices",
                "property list uchar float texcoord",
                "end_header",
                "0 0 0", "1 0 0", "1 1 0", "0 1 0",
                "3 0 1 2 6 0 0 1 0 1 1",
                "3 0 2 3 6 0 0 1 1 0 1",
            ]) + "\n")
        self._check(read_ply(path))
        if native.available():
            self._check(native.load_ply_native(path))


class TestLandmarkSniffing:
    """set_landmark_indices_from_any file-format branches
    (reference serialization.py:372-407)."""

    def _mesh(self):
        v, f = box(1.0)
        return Mesh(v=v, f=f.astype(np.uint32))

    def test_json_landmarks(self, tmp_path):
        import json

        m = self._mesh()
        path = str(tmp_path / "lm.json")
        with open(path, "w") as fh:
            json.dump({"corner": [-0.5, -0.5, -0.5]}, fh)
        m.set_landmark_indices_from_any(path)
        assert "corner" in m.landm

    def test_pkl_landmarks(self, tmp_path):
        import pickle

        m = self._mesh()
        path = str(tmp_path / "lm.pkl")
        with open(path, "wb") as fh:
            pickle.dump({"top": [0.5, 0.5, 0.5]}, fh)
        m.set_landmark_indices_from_any(path)
        assert "top" in m.landm

    def test_yaml_landmarks(self, tmp_path):
        yaml = pytest.importorskip("yaml")
        m = self._mesh()
        path = str(tmp_path / "lm.yaml")
        with open(path, "w") as fh:
            yaml.safe_dump({"side": [0.5, -0.5, 0.5]}, fh)
        m.set_landmark_indices_from_any(path)
        assert "side" in m.landm

    def _lmrk_file(self, tmp_path):
        # CAESAR layout: _scale/_translate/_rotation header then named
        # landmark rows whose coordinates are stored (z, x, y) — the
        # loader swizzles data[1], data[2], data[0] into xyz
        # (reference serialization.py:343-361)
        path = str(tmp_path / "subject.lmrk")
        with open(path, "w") as fh:
            fh.write(
                "_scale 1.0\n"
                "_translate 0.0 0.0 0.0\n"
                "_rotation 1 0 0 0 1 0 0 0 1\n"
                "\n"
                "Sellion 0.5 0.5 0.5\n"          # -> xyz (0.5, 0.5, 0.5)
                "Rt.Acromion -0.5 -0.5 -0.5\n"
                "Missing 0.0 0.0 0.0\n"          # zero rows filtered out
            )
        return path

    def test_lmrk_file_loads_with_swizzle(self, tmp_path):
        m = self._mesh()
        m.set_landmark_indices_from_lmrkfile(self._lmrk_file(tmp_path))
        assert set(m.landm) == {"Sellion", "Rt.Acromion"}  # zero row dropped
        np.testing.assert_allclose(m.landm_xyz["Sellion"], [0.5, 0.5, 0.5])
        np.testing.assert_allclose(
            m.landm_xyz["Rt.Acromion"], [-0.5, -0.5, -0.5]
        )
        np.testing.assert_allclose(m.caesar_rotation_matrix, np.eye(3))

    def test_lmrk_sniffed_by_content_not_extension(self, tmp_path):
        import shutil

        m = self._mesh()
        # sniffing keys on the _scale/_translate/_rotation header, so an
        # arbitrary extension must still route to the lmrk loader
        path = str(tmp_path / "landmarks.dat")
        shutil.copy(self._lmrk_file(tmp_path), path)
        m.set_landmark_indices_from_any(path)
        assert set(m.landm) == {"Sellion", "Rt.Acromion"}

    def test_lmrk_swizzle_maps_zxy_storage(self, tmp_path):
        # asymmetric row proves the (z, x, y) -> (x, y, z) mapping: the
        # stored triple (a, b, c) must surface as xyz == (b, c, a)
        path = str(tmp_path / "s.lmrk")
        with open(path, "w") as fh:
            fh.write(
                "_scale 1.0\n_translate 0 0 0\n"
                "_rotation 1 0 0 0 1 0 0 0 1\n"
                "P 0.5 -0.5 0.5\n"
            )
        m = self._mesh()
        m.set_landmark_indices_from_lmrkfile(path)
        np.testing.assert_allclose(m.landm_xyz["P"], [-0.5, 0.5, 0.5])

    def test_unknown_format_raises(self, tmp_path):
        m = self._mesh()
        path = str(tmp_path / "lm.bin")
        with open(path, "wb") as fh:
            fh.write(b"\x00\x01\x02garbage")
        with pytest.raises(SerializationError, match="unknown format"):
            m.set_landmark_indices_from_any(path)
