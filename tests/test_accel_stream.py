"""mesh_tpu.accel streamed rope kernel: bit-identity, routing, knobs.

The load-bearing claims under test (ISSUE 9 acceptance):

- The streamed (HBM leaves, double-buffered DMA) Pallas rope kernel is
  bit-identical to the resident kernel in interpret mode — on random
  soups, degenerate meshes, and (tier-2) a >=1M-face sphere, at any ring
  depth.
- pair_tests stay sub-linear in F at the million-face scale the
  streamed variant exists for.
- The VMEM-budget routing picks resident below the measured budget,
  stream above it, honours the force hatch, and the kill switch
  restores the legacy 64k ceiling.
- A cached index whose leaf size disagrees with tile_f is rebuilt only
  when asked (the facade's safety net); explicitly passed mismatched
  indexes still raise.
- stream_tile_params applies cache file > default, then the
  MESH_TPU_BVH_STREAM_BUFFERS override.
- perfcheck grades the accel_stream_proxy band and the committed golden
  meets acceptance.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp                                   # noqa: E402

from mesh_tpu.accel.build import build_bvh                # noqa: E402
from mesh_tpu.accel.pallas_bvh import (                   # noqa: E402
    closest_point_pallas_bvh,
)
from mesh_tpu.accel.pallas_stream import (                # noqa: E402
    STREAM_ROW_PAD,
    STREAM_ROWS,
    closest_point_pallas_bvh_stream,
    stream_vmem_bytes,
)
from mesh_tpu.accel.traverse import (                     # noqa: E402
    PALLAS_BVH_MAX_FACES,
    pallas_bvh_max_faces,
    pallas_bvh_variant,
    resident_rows_bytes,
)
from mesh_tpu.query.autotune import _sphere_mesh          # noqa: E402
from mesh_tpu.query.closest_point import (                # noqa: E402
    closest_faces_and_points,
)

_IDENTICAL_KEYS = ("face", "point", "sqdist", "part")


def _dense(v, f, q):
    res = closest_faces_and_points(jnp.asarray(v), jnp.asarray(f),
                                   jnp.asarray(q))
    return {k: np.asarray(val) for k, val in res.items()}


def _random_soup(seed, n_v=200, n_f=600, n_q=150, spread=1.0, shift=0.0):
    rng = np.random.default_rng(seed)
    v = (rng.normal(size=(n_v, 3)) * spread + shift).astype(np.float32)
    f = rng.integers(0, n_v, size=(n_f, 3)).astype(np.int32)
    q = (rng.normal(size=(n_q, 3)) * spread * 1.5 + shift).astype(
        np.float32)
    return v, f, q


def _degenerate_mesh(n_q=120):
    """Slivers, duplicated faces, zero-area (repeated-vertex) faces —
    the tie-heavy classes where a merge-order bug would show first."""
    rng = np.random.default_rng(7)
    v = rng.normal(size=(60, 3)).astype(np.float32)
    v[10] = v[9] + np.float32(1e-7)
    faces = [rng.integers(0, 60, size=3) for _ in range(80)]
    faces += [[9, 10, k] for k in range(5)]          # sliver family
    faces += [[3, 3, 17], [5, 5, 5]]                 # zero-area
    faces += [[1, 2, 4], [1, 2, 4], [1, 2, 4]]       # duplicates (ties)
    f = np.asarray(faces, np.int32)
    q = rng.normal(size=(n_q, 3)).astype(np.float32)
    return v, f, q


def _surface_queries(n_q, seed=21, jitter=0.05):
    """Near-surface unit-sphere queries — the scan-registration regime
    whose Morton tiles are compact enough for tile-granular pruning."""
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(n_q, 3))
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    q *= 1.0 + jitter * rng.normal(size=(n_q, 1))
    return q.astype(np.float32)


def _run_pair(v, f, q, n_buffers=2, tile_q=64, tile_f=256):
    resident = closest_point_pallas_bvh(
        v, f, q, tile_q=tile_q, tile_f=tile_f, interpret=True)
    streamed = closest_point_pallas_bvh_stream(
        v, f, q, tile_q=tile_q, tile_f=tile_f, n_buffers=n_buffers,
        interpret=True)
    return resident, streamed


# ---------------------------------------------------------------------------
# bit-identity with the resident kernel (interpret mode — chip-free)


@pytest.mark.parametrize("n_buffers", [2, 4])
@pytest.mark.parametrize("seed,shift", [(0, 0.0), (2, 50.0)])
def test_stream_bit_identical_random(n_buffers, seed, shift):
    v, f, q = _random_soup(seed, shift=shift)
    v = np.asarray(v, np.float32)
    f = np.asarray(f, np.int32)
    resident, streamed = _run_pair(v, f, q, n_buffers=n_buffers)
    for key in _IDENTICAL_KEYS:
        assert np.array_equal(np.asarray(resident[key]),
                              np.asarray(streamed[key])), \
            "streamed diverges from resident on %r" % key
    # stale refill bounds visit a superset of the resident's leaves
    assert (np.asarray(streamed["pair_tests"]).sum()
            >= np.asarray(resident["pair_tests"]).sum())


def test_stream_bit_identical_degenerate():
    v, f, q = _degenerate_mesh()
    resident, streamed = _run_pair(v, f, q)
    for key in _IDENTICAL_KEYS:
        assert np.array_equal(np.asarray(resident[key]),
                              np.asarray(streamed[key]))


def test_stream_exact_vs_dense_up_to_ties():
    v, f = _sphere_mesh(4000)
    v = np.asarray(v, np.float32)
    f = np.asarray(f, np.int32)
    q = _surface_queries(200)
    ref = _dense(v, f, q)
    out = closest_point_pallas_bvh_stream(v, f, q, tile_q=64, tile_f=256,
                                          interpret=True)
    sq = np.asarray(out["sqdist"])
    np.testing.assert_allclose(sq, ref["sqdist"], rtol=1e-5, atol=1e-7)
    diff = np.asarray(out["face"]) != ref["face"]
    assert np.allclose(sq[diff], ref["sqdist"][diff], rtol=1e-5, atol=1e-7)
    assert bool(np.asarray(out["tight"]).all())


# ---------------------------------------------------------------------------
# argument validation + index rebuild semantics


def test_stream_validates_tile_f_and_buffers():
    v, f, q = _random_soup(1)
    with pytest.raises(ValueError, match="tile_f"):
        closest_point_pallas_bvh_stream(v, f, q, tile_f=100,
                                        interpret=True)
    with pytest.raises(ValueError, match="n_buffers"):
        closest_point_pallas_bvh_stream(v, f, q, n_buffers=1,
                                        interpret=True)


def test_stream_mismatched_index_raises_unless_rebuild():
    v, f, q = _random_soup(3)
    v = np.asarray(v, np.float32)
    f = np.asarray(f, np.int32)
    fine = build_bvh(v, f, leaf_size=8)
    with pytest.raises(ValueError, match="leaf_size"):
        closest_point_pallas_bvh_stream(v, f, q, tile_f=256,
                                        interpret=True, index=fine)
    rebuilt = closest_point_pallas_bvh_stream(
        v, f, q, tile_f=256, interpret=True, index=fine,
        rebuild_mismatched=True)
    fresh = closest_point_pallas_bvh_stream(v, f, q, tile_f=256,
                                            interpret=True)
    for key in _IDENTICAL_KEYS:
        assert np.array_equal(np.asarray(rebuilt[key]),
                              np.asarray(fresh[key]))


def test_resident_rebuild_mismatched_matches_fresh():
    from mesh_tpu.accel.pallas_bvh import closest_point_pallas_bvh as cp

    v, f, q = _random_soup(4)
    v = np.asarray(v, np.float32)
    f = np.asarray(f, np.int32)
    fine = build_bvh(v, f, leaf_size=8)
    rebuilt = cp(v, f, q, tile_f=256, interpret=True, index=fine,
                 rebuild_mismatched=True)
    fresh = cp(v, f, q, tile_f=256, interpret=True)
    for key in _IDENTICAL_KEYS:
        assert np.array_equal(np.asarray(rebuilt[key]),
                              np.asarray(fresh[key]))


# ---------------------------------------------------------------------------
# VMEM-budget routing + knobs


def test_stream_vmem_bytes_shape():
    assert STREAM_ROWS == 19 and STREAM_ROW_PAD == 24
    assert stream_vmem_bytes(128, 256, 2) == (
        2 * STREAM_ROW_PAD * 256 * 4 + 6 * 128 * 4)
    # ring grows linearly with depth, query columns don't
    assert (stream_vmem_bytes(128, 256, 4) - stream_vmem_bytes(128, 256, 2)
            == 2 * STREAM_ROW_PAD * 256 * 4)


def test_variant_budget_routing(monkeypatch):
    monkeypatch.delenv("MESH_TPU_BVH_STREAM", raising=False)
    monkeypatch.delenv("MESH_TPU_BVH_STREAM_FORCE", raising=False)
    monkeypatch.setenv("MESH_TPU_BVH_STREAM_VMEM_MB", "12")
    # 19 * 131072 * 4 B ~ 9.5 MiB fits a 12 MiB budget; the next
    # power-of-two padding doubles it past the budget
    assert resident_rows_bytes(131072) <= 12 * 2 ** 20
    assert pallas_bvh_variant(131072) == "resident"
    assert pallas_bvh_variant(131073) == "stream"
    assert pallas_bvh_max_faces() == 131072
    # a starved budget streams everything
    monkeypatch.setenv("MESH_TPU_BVH_STREAM_VMEM_MB", "0.1")
    assert pallas_bvh_variant(4096) == "stream"
    assert pallas_bvh_max_faces() < 131072


def test_variant_kill_switch_restores_legacy_ceiling(monkeypatch):
    monkeypatch.setenv("MESH_TPU_BVH_STREAM", "0")
    assert pallas_bvh_variant(PALLAS_BVH_MAX_FACES) == "resident"
    assert pallas_bvh_variant(PALLAS_BVH_MAX_FACES + 1) is None


def test_variant_force_hatch(monkeypatch):
    monkeypatch.delenv("MESH_TPU_BVH_STREAM", raising=False)
    monkeypatch.setenv("MESH_TPU_BVH_STREAM_FORCE", "1")
    assert pallas_bvh_variant(1024) == "stream"


def test_stream_buffers_knob(monkeypatch):
    from mesh_tpu.utils.dispatch import bvh_stream_buffers

    monkeypatch.delenv("MESH_TPU_BVH_STREAM_BUFFERS", raising=False)
    assert bvh_stream_buffers(default=3) == 3
    monkeypatch.setenv("MESH_TPU_BVH_STREAM_BUFFERS", "5")
    assert bvh_stream_buffers(default=3) == 5
    monkeypatch.setenv("MESH_TPU_BVH_STREAM_BUFFERS", "1")
    assert bvh_stream_buffers(default=3) == 2      # clamped to >= 2


def test_stream_tile_params_cache_and_override(tmp_path, monkeypatch):
    from mesh_tpu.query import autotune

    cache = tmp_path / "stream_tiles_cpu_test.json"
    monkeypatch.setattr(autotune, "_stream_cache_path",
                        lambda: str(cache))
    monkeypatch.delenv("MESH_TPU_BVH_STREAM_BUFFERS", raising=False)

    # no cache file -> conservative default
    monkeypatch.setattr(autotune, "_stream_measured", None)
    assert autotune.stream_tile_params() == autotune.STREAM_DEFAULT_TILES

    # cached measurement wins
    cache.write_text(json.dumps(
        {"tile_q": 256, "tile_f": 512, "n_buffers": 3}))
    monkeypatch.setattr(autotune, "_stream_measured", None)
    assert autotune.stream_tile_params() == (256, 512, 3)

    # env override applies on top of the cached n_buffers
    monkeypatch.setenv("MESH_TPU_BVH_STREAM_BUFFERS", "4")
    assert autotune.stream_tile_params() == (256, 512, 4)

    # a corrupt cache (tile_f not lane-aligned) falls back to default
    monkeypatch.delenv("MESH_TPU_BVH_STREAM_BUFFERS", raising=False)
    cache.write_text(json.dumps(
        {"tile_q": 256, "tile_f": 100, "n_buffers": 3}))
    monkeypatch.setattr(autotune, "_stream_measured", None)
    assert autotune.stream_tile_params() == autotune.STREAM_DEFAULT_TILES


# ---------------------------------------------------------------------------
# perfcheck stream band (stdlib-only surface)


def _stream_rec(value=0.83, checksum=-89.0493, faces=209304):
    return {"metric": "accel_stream_proxy_skip_ratio", "value": value,
            "unit": "pair_tests_skipped_frac", "checksum": checksum,
            "faces": faces, "resident_match": True}


def test_perfcheck_stream_band_pass_and_fail():
    from mesh_tpu.obs.perf import perfcheck

    golden = _stream_rec()
    doc = {"metric": "x", "value": None, "unit": None,
           "stream": _stream_rec()}
    rc, lines = perfcheck(doc, stream_golden=golden)
    assert rc == 0
    assert any("ok stream pair-tests-skipped" in ln for ln in lines)

    doc_bad = {"metric": "x", "value": None, "unit": None,
               "stream": _stream_rec(value=0.4)}
    rc, lines = perfcheck(doc_bad, stream_golden=golden)
    assert rc == 1
    assert any(ln.startswith("FAIL stream pair-tests-skipped")
               for ln in lines)

    drift = {"metric": "x", "value": None, "unit": None,
             "stream": _stream_rec(checksum=-89.0)}
    rc, lines = perfcheck(drift, stream_golden=golden)
    assert rc == 1
    assert any("FAIL stream checksum" in ln for ln in lines)

    rc, lines = perfcheck({"metric": "x", "value": None, "unit": None},
                          stream_golden=golden)
    assert rc == 1
    assert any("FAIL stream" in ln for ln in lines)


def test_extract_records_stream_slot():
    from mesh_tpu.obs.perf import extract_records

    partial = {"kind": "bench_partial", "stages": {
        "accel_stream_proxy": {"status": "ok", "record": _stream_rec()}}}
    assert extract_records(partial)["stream"]["value"] == 0.83
    final = {"metric": "x", "value": 1.0, "stream": _stream_rec(value=0.8)}
    assert extract_records(final)["stream"]["value"] == 0.8


def test_committed_stream_golden_meets_acceptance():
    """The committed golden IS the acceptance evidence: the streamed
    kernel walks a mesh past the resident VMEM budget with most pair
    tests pruned and the resident bit-match asserted in-stage."""
    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        "accel_stream_golden.json")
    with open(path) as fh:
        rec = json.load(fh)
    assert rec["faces"] >= 200000
    assert rec["faces"] > 131072           # past the resident budget
    assert rec["value"] >= 0.7
    assert rec["resident_match"] is True
    assert rec["pair_tests_per_query"] < rec["faces"]
    assert rec["n_buffers"] >= 2


# ---------------------------------------------------------------------------
# scale (tier-2): the whole point — >=1M faces, no ceiling, sub-linear


@pytest.mark.slow
def test_stream_million_faces_bit_identical_and_sublinear():
    q = _surface_queries(4096)
    sizes = (262144, 1_050_000)
    pair_totals, faces = [], []
    outs = {}
    for n_target in sizes:
        v, f = _sphere_mesh(n_target)
        v = np.asarray(v, np.float32)
        f = np.asarray(f, np.int32)
        out = closest_point_pallas_bvh_stream(
            v, f, q, tile_q=128, tile_f=256, interpret=True)
        pair_totals.append(int(np.asarray(out["pair_tests"]).sum()))
        faces.append(int(f.shape[0]))
        outs[n_target] = (v, f, out)

    assert faces[-1] >= 1_000_000
    # sub-linear in F: 4x the faces must cost well under 4x the pair
    # tests (tile-granular pruning tightens as leaves shrink)
    growth = pair_totals[1] / float(pair_totals[0])
    f_growth = faces[1] / float(faces[0])
    assert growth < 0.8 * f_growth, \
        "pair tests grew %.2fx for %.2fx faces — not sub-linear" % (
            growth, f_growth)
    assert pair_totals[1] < 0.2 * len(q) * faces[1]

    # bit-identity against the resident kernel at the million-face scale
    # (interpret mode has no VMEM ceiling, so the resident kernel still
    # runs and serves as the reference)
    v, f, streamed = outs[sizes[-1]]
    resident = closest_point_pallas_bvh(v, f, q, tile_q=128, tile_f=256,
                                        interpret=True)
    for key in _IDENTICAL_KEYS:
        assert np.array_equal(np.asarray(resident[key]),
                              np.asarray(streamed[key])), \
            "million-face streamed result diverges on %r" % key
