"""Record/replay acceptance: traces, checksums, shadow diff, synthesis.

The contract under test (doc/observability.md "Record/replay"):

- a trace round-trips through write/load byte-faithfully and refuses
  schemas newer than the reader;
- ledger dumps, live ledgers, and schema>=2 incidents all convert to
  replayable traces with rebased admit offsets;
- the SAME trace replayed twice — against real QueryServices under a
  fake clock — produces the SAME admission-sequence checksum, equal to
  the trace's canonical sequence hash, invariant to ``speed`` but NOT
  to a deadline override;
- ``mesh-tpu replay diff`` attributes a fault-injected dispatch
  slowdown to the 'dispatch' stage with rc 1;
- the perfcheck replay band hard-fails on checksum drift or a missing
  checksum;
- the MESH_TPU_REPLAY_TRACE knob streams ledger closes into a capture
  file with no code changes;
- the committed benchmarks/replay_golden.json matches what the
  replay_proxy stage produces today.

Everything here is jax-free and fake-clocked — the whole module runs
in seconds on a machine that has never seen a TPU.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mesh_tpu.obs import prof, replay
from mesh_tpu.obs.ledger import LEDGER_SCHEMA, LatencyLedger
from mesh_tpu.obs.metrics import Registry
from mesh_tpu.serve import (
    HealthMonitor,
    QueryService,
    Rung,
    ServeResult,
    run_trace_replay,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# helpers


class FakeClock(object):
    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)
        return self.t


def _fake_pair():
    """A (clock, sleep) pair over shared virtual time."""
    t = [0.0]

    def clock():
        return t[0]

    def sleep(dt):
        t[0] += max(dt, 0.0)

    return clock, sleep


def _plain_service(**kw):
    faces = np.zeros((1, 4), np.uint32)
    answer = np.zeros((4, 3), np.float64)

    def _ok(mesh, points, chunk, timeout):
        return ServeResult(faces, answer, "replay-ok", certified=True)

    kw.setdefault("workers", 2)
    kw.setdefault("ladder", [Rung("replay-ok", _ok)])
    kw.setdefault("health", HealthMonitor(watchdog=False))
    kw.setdefault("max_queue_per_tenant", 8192)
    kw.setdefault("default_deadline_s", 30.0)
    return QueryService(**kw)


_PTS = np.zeros((4, 3), np.float32)


def _ledger_rows(n=3, t0=500.0, dispatch_s=0.002):
    """Synthetic closed ledger rows via a private fake-clock ledger."""
    led = LatencyLedger(capacity=64, registry=Registry(),
                       clock=(clk := FakeClock(t0)))
    for i in range(n):
        rec = led.open(tenant="t%d" % (i % 2), op="closest_point",
                       bucket=256, q=100 + i, deadline_s=0.5, priority=0)
        clk.advance(0.001)
        rec.stamp("queue")
        clk.advance(dispatch_s)
        rec.stamp("dispatch")
        clk.advance(0.003)
        rec.stamp("device")
        clk.advance(0.0005)
        led.close(rec, backend="xla")
        clk.advance(0.05)
    return led


def _run_cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    return subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli"] + list(argv),
        capture_output=True, text=True, timeout=180, env=env, cwd=_REPO)


# ---------------------------------------------------------------------------
# trace files: round-trip and refusal


def test_trace_round_trip(tmp_path):
    trace = replay.synth_stampede(seed=3)
    path = str(tmp_path / "trace.jsonl")
    n = replay.write_trace(trace, path)
    assert n == len(trace["records"]) > 0
    loaded = replay.load_trace(path)
    assert loaded["source"] == trace["source"]
    assert loaded["records"] == trace["records"]
    # and the identity that makes diffs meaningful: the checksum survives
    assert replay.sequence_checksum(replay.admission_events(loaded)) == \
        replay.sequence_checksum(replay.admission_events(trace))


def test_load_trace_refuses_future_schema(tmp_path):
    path = str(tmp_path / "future.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"kind": "mesh_tpu_trace",
                             "schema": replay.TRACE_SCHEMA + 1,
                             "source": "future"}) + "\n")
        fh.write(json.dumps({"t": 0.0}) + "\n")
    with pytest.raises(replay.ReplayError, match="newer than supported"):
        replay.load_trace(path)


def test_load_trace_refuses_garbage(tmp_path):
    headerless = str(tmp_path / "no_header.jsonl")
    with open(headerless, "w") as fh:
        fh.write(json.dumps({"t": 0.0}) + "\n")
    with pytest.raises(replay.ReplayError, match="not a trace file"):
        replay.load_trace(headerless)
    with pytest.raises(replay.ReplayError, match="cannot read"):
        replay.load_trace(str(tmp_path / "missing.jsonl"))
    malformed = str(tmp_path / "malformed.jsonl")
    with open(malformed, "w") as fh:
        fh.write(json.dumps({"kind": "mesh_tpu_trace", "schema": 1,
                             "source": "x"}) + "\n")
        fh.write("{not json\n")
    with pytest.raises(replay.ReplayError, match="malformed"):
        replay.load_trace(malformed)


# ---------------------------------------------------------------------------
# converters: ledger dumps, live ledgers, incidents


def test_trace_from_ledger_rebases_offsets(tmp_path):
    led = _ledger_rows(n=3, t0=500.0)
    trace = replay.trace_from_ledger(led)
    offsets = [rec["t"] for rec in trace["records"]]
    # monotonic-clock origin (t=500) never leaks into the trace
    assert offsets[0] == 0.0
    assert offsets == sorted(offsets)
    assert all(t < 10.0 for t in offsets)
    assert trace["records"][0]["tenant"] == "t0"
    assert trace["records"][0]["deadline_s"] == 0.5
    # a dump_jsonl file converts identically (schema stamp and all)
    dump = str(tmp_path / "ledger.jsonl")
    led.dump_jsonl(dump)
    from_file = replay.trace_from_ledger(dump)
    assert [r["t"] for r in from_file["records"]] == offsets


def test_trace_from_ledger_requires_admit_stamps():
    with pytest.raises(replay.ReplayError, match="no ledger rows"):
        replay.trace_from_ledger([{"tenant": "x"}], name="empty")


def test_trace_from_incident_schema_gate():
    led = _ledger_rows(n=2)
    doc = {"kind": "incident", "schema_version": 3, "reason": "slo_fast_burn",
           "ledger": led.records()}
    trace = replay.trace_from_incident(doc)
    assert trace["source"] == "incident:slo_fast_burn"
    assert len(trace["records"]) == 2
    with pytest.raises(replay.ReplayError, match="schema_version"):
        replay.trace_from_incident({"kind": "incident", "schema_version": 1})
    with pytest.raises(replay.ReplayError, match="not an incident"):
        replay.trace_from_incident({"kind": "metrics"})


# ---------------------------------------------------------------------------
# satellite: dump_jsonl schema stamp, prof accepts-and-checks


def test_dump_jsonl_stamps_schema_and_prof_accepts(tmp_path):
    led = _ledger_rows(n=2)
    path = str(tmp_path / "dump.jsonl")
    led.dump_jsonl(path)
    with open(path) as fh:
        rows = [json.loads(ln) for ln in fh]
    assert all(row["schema"] == LEDGER_SCHEMA for row in rows)
    # the in-ring rows stay unstamped: the version belongs to the file
    assert all("schema" not in row for row in led.records())
    stats = prof.load(path)
    assert stats["stages"]["dispatch"]["count"] == 2


def test_prof_refuses_newer_ledger_schema(tmp_path):
    led = _ledger_rows(n=2)
    path = str(tmp_path / "future.jsonl")
    with open(path, "w") as fh:
        for row in led.records():
            fh.write(json.dumps(dict(row, schema=LEDGER_SCHEMA + 1)) + "\n")
    with pytest.raises(prof.ProfError, match="newer than supported"):
        prof.load(path)


# ---------------------------------------------------------------------------
# determinism: same trace twice => same admission sequence


def test_live_replay_checksum_deterministic():
    trace = replay.synth_mix(seed=7)
    clock, sleep = _fake_pair()
    reports = []
    for _ in range(2):
        service = _plain_service()
        try:
            reports.append(run_trace_replay(
                service, object(), _PTS, trace, deadline_s=30.0,
                clock=clock, sleep=sleep))
        finally:
            service.stop(write_stats=False)
    first, second = reports
    assert first["checksum"] == second["checksum"]
    assert first["checksum"] == replay.sequence_checksum(
        replay.admission_events(trace, deadline_s=30.0))
    assert first["admissions"] == len(trace["records"])
    assert first["ok"] == len(trace["records"])
    assert first["shed"] == 0 and first["deadline_failures"] == 0


def test_checksum_speed_invariant_deadline_sensitive():
    trace = replay.synth_stampede(seed=5)
    base = replay.null_replay(trace)
    warped = replay.null_replay(trace, speed=4.0)
    # time-warp repaces the same sequence: shorter window, same identity
    assert warped["checksum"] == base["checksum"]
    assert warped["paced_s"] == pytest.approx(base["paced_s"] / 4.0,
                                              abs=1e-3)
    # a deadline override IS a different workload, and the checksum says so
    overridden = replay.null_replay(trace, deadline_s=30.0)
    assert overridden["checksum"] != base["checksum"]
    with pytest.raises(replay.ReplayError, match="speed"):
        replay.null_replay(trace, speed=0.0)


def test_replay_moves_metrics_and_store_keys():
    trace = {"schema": 1, "source": "synth:test", "records": [
        {"t": 0.0, "tenant": "a", "priority": 0, "deadline_s": 5.0,
         "store_key": "sha256:abc"},
        {"t": 0.01, "tenant": "b", "priority": 1, "deadline_s": 5.0},
    ]}
    seen = []

    class _Future(object):
        def result(self, timeout=None):
            import types
            return types.SimpleNamespace(
                latency_s=0.001, rung="ok", retries=0,
                deadline_missed=False, approximate=False)

    class _Spy(object):
        def submit(self, mesh, points, **kw):
            seen.append((mesh, kw["tenant"], kw["priority"]))
            return _Future()

    clock, sleep = _fake_pair()
    report = run_trace_replay(_Spy(), None, _PTS, trace,
                              clock=clock, sleep=sleep)
    # mesh=None lets the captured store_key route through the store path
    assert seen == [("sha256:abc", "a", 0), (None, "b", 1)]
    assert report["loop"] == "replay" and report["source"] == "synth:test"
    from mesh_tpu.obs.metrics import REGISTRY
    counter = REGISTRY.get("mesh_tpu_replay_requests_total")
    assert counter is not None
    assert counter.value(tenant="a", source="synth:test") >= 1


# ---------------------------------------------------------------------------
# synthesis


def test_synth_generators_deterministic_and_sorted():
    for kind in sorted(replay.SYNTH_KINDS):
        a = replay.synthesize(kind)
        b = replay.synthesize(kind)
        assert a == b, "synth %r is not deterministic" % kind
        offsets = [rec["t"] for rec in a["records"]]
        assert offsets == sorted(offsets)
        assert a["records"], "synth %r emitted an empty trace" % kind
        assert a["source"].startswith("synth:")
    with pytest.raises(replay.ReplayError, match="unknown synth kind"):
        replay.synthesize("nope")
    # the adversarial shapes carry their regeneration tags
    assert all(r["shape"] == "volume_fill"
               for r in replay.synth_prune_defeat()["records"])
    assert all(r["shape"] == "degenerate_mesh"
               for r in replay.synth_degenerate()["records"])
    # stampede: every tenant admits within 1 ms of its burst instant
    burst = [r for r in replay.synth_stampede(tenants=4)["records"]
             if r["t"] < 0.002]
    assert len({r["tenant"] for r in burst}) == 4


# ---------------------------------------------------------------------------
# shadow diff: fault-injected dispatch slowdown attributed with rc 1


def _shadow_report(trace, dispatch_s, path):
    def model(rec, d=dispatch_s):
        return {"queue": 0.001, "dispatch": d, "device": 0.003,
                "respond": 0.0005}
    report = replay.null_replay(trace)
    replay.attach_stage_stats(report, replay.shadow_rows(trace, model))
    with open(path, "w") as fh:
        json.dump(report, fh)
    return report


def test_replay_diff_attributes_dispatch_slowdown(tmp_path):
    trace = replay.synth_stampede(seed=9)
    a = str(tmp_path / "base.json")
    b = str(tmp_path / "slow.json")
    _shadow_report(trace, 0.002, a)
    _shadow_report(trace, 0.052, b)     # fault-injected +50 ms dispatch
    proc = _run_cli("replay", "diff", a, b, "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    fail_lines = [ln for ln in doc["lines"] if "'dispatch'" in ln]
    assert fail_lines, doc["lines"]
    assert any("checksums match" in ln for ln in doc["lines"])


def test_replay_diff_checksum_mismatch_fails(tmp_path):
    a = str(tmp_path / "a.json")
    b = str(tmp_path / "b.json")
    _shadow_report(replay.synth_stampede(seed=9), 0.002, a)
    _shadow_report(replay.synth_steady(seed=1), 0.002, b)
    proc = _run_cli("replay", "diff", a, b, "--json")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert any("DIFFERENT workloads" in ln for ln in doc["lines"])


def test_shadow_rows_refuse_unknown_stage():
    trace = replay.synth_steady(duration_s=0.2)
    with pytest.raises(replay.ReplayError, match="unknown stage"):
        replay.shadow_rows(trace, lambda rec: {"warp_drive": 1.0})


# ---------------------------------------------------------------------------
# CLI rc matrix


def test_replay_cli_run_and_synth(tmp_path):
    trace_path = str(tmp_path / "mix.jsonl")
    proc = _run_cli("replay", "synth", "stampede", "--out", trace_path)
    assert proc.returncode == 0, proc.stderr
    run1 = _run_cli("replay", "run", trace_path, "--json")
    run2 = _run_cli("replay", "run", trace_path, "--json", "--speed", "3")
    assert run1.returncode == 0 and run2.returncode == 0
    r1, r2 = json.loads(run1.stdout), json.loads(run2.stdout)
    # twice-replayed trace: same checksum, machine-checked (speed-warped)
    assert r1["checksum"] == r2["checksum"]
    assert r2["paced_s"] < r1["paced_s"]


def test_replay_cli_unreadable_is_rc2(tmp_path):
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as fh:
        fh.write("this is not a trace\n")
    assert _run_cli("replay", "run", bad).returncode == 2
    assert _run_cli("replay", "run",
                    str(tmp_path / "missing.jsonl")).returncode == 2
    assert _run_cli("replay", "synth", "nope").returncode == 2


# ---------------------------------------------------------------------------
# perfcheck replay band


def _band(cand_replay, gold):
    from mesh_tpu.obs.perf import perfcheck
    doc = {"replay": cand_replay} if cand_replay is not None else \
        {"metric": "x", "value": None, "unit": None, "vs_baseline": None}
    return perfcheck(doc, replay_golden=gold)


def test_perfcheck_replay_band():
    gold = {"metric": "replay_admissions", "value": 250,
            "checksum": 3558183080.0}
    rc, lines = _band(dict(gold), gold)
    assert rc == 0
    assert any("ok replay admissions" in ln for ln in lines)
    # a candidate with no replay record at all is a hard FAIL
    rc, lines = _band(None, gold)
    assert rc == 1
    assert any("FAIL replay" in ln for ln in lines)
    # checksum drift is a hard FAIL even with the value in band
    rc, lines = _band(dict(gold, checksum=gold["checksum"] + 1), gold)
    assert rc == 1
    assert any("FAIL replay admission-sequence checksum" in ln
               for ln in lines)
    # a candidate that cannot prove determinism is a hard FAIL
    rc, lines = _band({"metric": "replay_admissions", "value": 250}, gold)
    assert rc == 1
    assert any("determinism unproven" in ln for ln in lines)
    # admission count below the floor fails
    rc, _ = _band(dict(gold, value=100), gold)
    assert rc == 1
    # record with no golden: informational note, rc 0
    from mesh_tpu.obs.perf import perfcheck
    rc, lines = perfcheck({"replay": dict(gold)})
    assert rc == 0
    assert any("make replay-golden" in ln for ln in lines)


# ---------------------------------------------------------------------------
# capture knob and listeners


def test_capture_knob_streams_closes(tmp_path, monkeypatch):
    path = str(tmp_path / "capture.jsonl")
    monkeypatch.setenv("MESH_TPU_REPLAY_TRACE", path)
    try:
        _ledger_rows(n=3)
    finally:
        replay.reset_capture()
        monkeypatch.delenv("MESH_TPU_REPLAY_TRACE")
    trace = replay.load_trace(path)
    assert trace["source"] == "capture"
    assert len(trace["records"]) == 3
    assert trace["records"][0]["t"] == 0.0


def test_trace_writer_listener(tmp_path):
    path = str(tmp_path / "listener.jsonl")
    led = LatencyLedger(capacity=16, registry=Registry(),
                       clock=(clk := FakeClock()))
    with replay.TraceWriter(path, source="live") as writer:
        led.add_listener(writer.observe)
        for _ in range(2):
            rec = led.open(tenant="w")
            clk.advance(0.01)
            led.close(rec)
        led.remove_listener(writer.observe)
        rec = led.open(tenant="w")
        led.close(rec)
    assert writer.written == 2
    assert len(replay.load_trace(path)["records"]) == 2


def test_listener_exceptions_are_swallowed():
    led = LatencyLedger(capacity=16, registry=Registry(),
                       clock=FakeClock())

    def bomb(row):
        raise RuntimeError("observer crash")

    led.add_listener(bomb)
    row = led.close(led.open(tenant="x"))   # must not raise
    assert row["outcome"] == "ok"


# ---------------------------------------------------------------------------
# golden acceptance: the committed artifact matches today's build


def _bench_stage(stage):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               MESH_TPU_REPLAY_TRACE="")
    return subprocess.run(
        [sys.executable, "bench.py", "--stage", stage],
        capture_output=True, text=True, timeout=180, env=env, cwd=_REPO)


def test_replay_proxy_stage_matches_golden():
    proc = _bench_stage("replay_proxy")
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout)
    with open(os.path.join(_REPO, "benchmarks", "replay_golden.json")) as fh:
        golden = json.load(fh)
    assert record["value"] == golden["value"]
    assert record["checksum"] == golden["checksum"]
    assert record["double_run"] == "checksum_equal"


def test_tuner_replay_stage_deterministic():
    a = _bench_stage("tuner_replay")
    assert a.returncode == 0, a.stderr[-2000:]
    rec_a = json.loads(a.stdout)
    b = _bench_stage("tuner_replay")
    assert b.returncode == 0, b.stderr[-2000:]
    rec_b = json.loads(b.stdout)
    assert rec_a["value"] == rec_b["value"]
    assert rec_a["checksum"] == rec_b["checksum"]
    assert rec_a["source"] == "synth:tuner_gym"
