"""Automated API-parity audit: every public symbol the reference's Python
modules define must be importable from the `psbody.mesh` drop-in shim.

The expected surface is extracted from the reference sources by AST (never
imported — the reference's compiled extensions don't exist here), so this
test IS the line-by-line completeness check: a reference symbol we drop
shows up as a named failure, and new reference-surface code can't regress
silently.
"""

import ast
import importlib
import os

import pytest

REFERENCE_ROOT = "/root/reference/mesh"

# reference module -> shim module that must expose its public surface
MODULE_MAP = {
    "mesh.py": "psbody.mesh.mesh",
    "search.py": "psbody.mesh.search",
    "lines.py": "psbody.mesh.lines",
    "sphere.py": "psbody.mesh.sphere",
    "colors.py": "psbody.mesh.colors",
    "texture.py": "psbody.mesh.texture",
    "arcball.py": "psbody.mesh.arcball",
    "landmarks.py": "psbody.mesh.landmarks",
    "processing.py": "psbody.mesh.processing",
    "utils.py": "psbody.mesh.utils",
    "errors.py": "psbody.mesh.errors",
    "fonts.py": "psbody.mesh.fonts",
    "meshviewer.py": "psbody.mesh.meshviewer",
    "geometry/barycentric_coordinates_of_projection.py":
        "psbody.mesh.geometry.barycentric_coordinates_of_projection",
    "geometry/triangle_area.py": "psbody.mesh.geometry.triangle_area",
    "geometry/cross_product.py": "psbody.mesh.geometry.cross_product",
    "geometry/tri_normals.py": "psbody.mesh.geometry.tri_normals",
    "geometry/rodrigues.py": "psbody.mesh.geometry.rodrigues",
    "geometry/vert_normals.py": "psbody.mesh.geometry.vert_normals",
    "topology/linear_mesh_transform.py":
        "psbody.mesh.topology.linear_mesh_transform",
    "topology/decimation.py": "psbody.mesh.topology.decimation",
    "topology/connectivity.py": "psbody.mesh.topology.connectivity",
    "topology/subdivision.py": "psbody.mesh.topology.subdivision",
    "serialization/serialization.py":
        "psbody.mesh.serialization.serialization",
}


def reference_surface(relpath):
    """(classes {name: [public methods]}, [public functions]) of a reference
    module, by parsing its source."""
    path = os.path.join(REFERENCE_ROOT, relpath)
    import warnings

    with warnings.catch_warnings():
        # the reference's own sources contain pre-3.12 escape sequences
        warnings.simplefilter("ignore", SyntaxWarning)
        tree = ast.parse(open(path, encoding="utf-8", errors="ignore").read())
    classes, functions = {}, []
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and not node.name.startswith("_"):
            classes[node.name] = [
                n.name for n in node.body
                if isinstance(n, ast.FunctionDef)
                and not n.name.startswith("_")
            ]
        elif isinstance(node, ast.FunctionDef) and not node.name.startswith("_"):
            functions.append(node.name)
    return classes, functions


@pytest.mark.skipif(
    not os.path.isdir(REFERENCE_ROOT), reason="reference checkout not present"
)
@pytest.mark.parametrize("relpath", sorted(MODULE_MAP))
def test_shim_module_covers_reference(relpath):
    classes, functions = reference_surface(relpath)
    mod = importlib.import_module(MODULE_MAP[relpath])
    missing = []
    for fn in functions:
        if not hasattr(mod, fn):
            missing.append(fn)
    for cls_name, methods in classes.items():
        cls = getattr(mod, cls_name, None)
        if cls is None:
            missing.append(cls_name)
            continue
        missing.extend(
            "%s.%s" % (cls_name, m) for m in methods if not hasattr(cls, m)
        )
    assert not missing, (
        "shim %s is missing reference symbols: %s"
        % (MODULE_MAP[relpath], ", ".join(missing))
    )
