"""Timing utilities (utils/profiling.py) — SURVEY.md section 5 gap-fill."""

import numpy as np
import jax.numpy as jnp

from mesh_tpu.utils.profiling import Timer, host_sync, time_fn


class TestHostSync:
    def test_returns_input_and_materializes(self):
        tree = {"a": jnp.arange(4), "b": [jnp.ones(2), 3.0, None]}
        out = host_sync(tree)
        assert out is tree

    def test_accepts_plain_python(self):
        assert host_sync([1, "x", None]) == [1, "x", None]


class TestTimer:
    def test_measures_elapsed(self):
        with Timer("t") as t:
            x = t.watch(jnp.sum(jnp.arange(100)))
        assert t.elapsed > 0
        assert int(x) == 4950

    def test_log_callback(self):
        lines = []
        with Timer("named", log=lines.append):
            pass
        assert len(lines) == 1 and lines[0].startswith("named:")

    def test_elapsed_recorded_when_body_raises(self):
        # PR-2 satellite: a raising body must still leave a measurement
        # (sync is skipped — the watched output may be half-built)
        t = Timer("boom")
        try:
            with t:
                t.watch(jnp.arange(4))
                raise RuntimeError("device flaked")
        except RuntimeError:
            pass
        assert t.elapsed is not None and t.elapsed > 0
        assert t.sync_elapsed is None

    def test_sync_elapsed_split(self):
        with Timer("s") as t:
            t.watch(jnp.sum(jnp.arange(1000)))
        assert t.sync_elapsed is not None and t.sync_elapsed >= 0
        assert t.elapsed >= t.sync_elapsed

    def test_sync_elapsed_none_without_watch(self):
        with Timer("n") as t:
            pass
        assert t.elapsed >= 0 and t.sync_elapsed is None


class TestTimeFn:
    def test_times_jax_fn(self):
        v = jnp.ones((64, 3))
        t = time_fn(lambda: (v * 2).sum(), reps=3, warmup=1)
        assert 0 < t < 10

    def test_times_plain_fn(self):
        t = time_fn(lambda: np.ones(8).sum(), reps=2)
        assert t >= 0
