"""Viewer-stack tests runnable headless.

Ports the reference's test styles: the arcball click/drag sequence with
hardcoded quaternion/matrix goldens (tests/test_arcball.py:13-74), the sphere
intersection-volume symmetry check (tests/test_spheres.py:9-15), and the
"spawn a real server process and check it speaks the protocol" approach
(tests/test_meshviewer.py:52-79) — adapted to the handshake-first design
(the port line prints before GL init, so the handshake is testable on a
headless box even though the GLUT window cannot open).
"""

import copy
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from mesh_tpu.viewer.arcball import (
    ArcBallT,
    Matrix3fMulMatrix3f,
    Matrix3fSetRotationFromQuat4f,
    Matrix3fT,
    Matrix4fSetRotationFromMatrix3f,
    Matrix4fT,
    Point2fT,
)


class TestArcball:
    def test_click_drag_sequence_matches_reference_goldens(self):
        """Two click+drag gestures; quaternions and transforms must match the
        reference's hardcoded values (tests/test_arcball.py:13-74)."""
        Transform = Matrix4fT()
        ThisRot = Matrix3fT()
        ball = ArcBallT(640, 480)

        LastRot = copy.copy(ThisRot)
        ball.click(Point2fT(500, 250))
        quat = ball.drag(Point2fT(475, 275))
        np.testing.assert_almost_equal(
            quat, [0.08438914, -0.08534209, -0.06240178, 0.99080837]
        )

        ThisRot = Matrix3fSetRotationFromQuat4f(quat)
        ThisRot = Matrix3fMulMatrix3f(LastRot, ThisRot)
        Transform = Matrix4fSetRotationFromMatrix3f(Transform, ThisRot)
        np.testing.assert_almost_equal(
            Transform,
            np.array([
                [0.97764552, -0.1380603, 0.15858325, 0.0],
                [0.10925253, 0.97796899, 0.17787792, 0.0],
                [-0.17964739, -0.15657592, 0.97119039, 0.0],
                [0.0, 0.0, 0.0, 1.0],
            ]),
        )

        LastRot = copy.copy(ThisRot)
        ball.click(Point2fT(350, 260))
        quat = ball.drag(Point2fT(450, 260))
        np.testing.assert_almost_equal(
            quat, [0.00710336, 0.31832787, 0.02679029, 0.94757545]
        )

        ThisRot = Matrix3fSetRotationFromQuat4f(quat)
        ThisRot = Matrix3fMulMatrix3f(LastRot, ThisRot)
        Transform = Matrix4fSetRotationFromMatrix3f(Transform, ThisRot)
        np.testing.assert_almost_equal(
            Transform,
            np.array([
                [0.88022292, -0.08322023, -0.46720669, 0.0],
                [0.14910145, 0.98314685, 0.10578787, 0.0],
                [0.45052907, -0.16277808, 0.8777966, 0.0],
                [0.0, 0.0, 0.0, 1.00000001],
            ]),
        )

    def test_no_motion_drag_is_null_quaternion(self):
        ball = ArcBallT(640, 480)
        ball.click(Point2fT(100, 100))
        assert np.allclose(ball.drag(Point2fT(100, 100)), 0.0)


class TestSphere:
    def test_intersection_is_symmetric(self):
        """reference tests/test_spheres.py:9-15."""
        from mesh_tpu.sphere import Sphere

        s0 = Sphere(np.array([0, 0, 0]), 1)
        for dd in np.linspace(0, 2, 10):
            s1 = Sphere(np.array([2 - dd, 0, 0]), 0.5)
            np.testing.assert_almost_equal(
                s0.intersection_vol(s1), s1.intersection_vol(s0)
            )

    def test_containment(self):
        from mesh_tpu.sphere import Sphere

        s = Sphere(np.zeros(3), 1.0)
        assert s.has_inside(np.array([0.5, 0, 0]))
        assert not s.has_inside(np.array([1.5, 0, 0]))


class TestLines:
    def test_colors_like_and_obj(self, tmp_path):
        from mesh_tpu.lines import Lines

        # 4 vertices: a 3-vertex polyline would make an RGB triple ambiguous
        # with per-vertex scalar weights (same dispatch as reference
        # lines.py:28-48, which keys on color.shape == (len(arr),))
        v = np.array([[0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0]], np.float64)
        e = np.array([[0, 1], [1, 2], [2, 3]], np.uint32)
        lines = Lines(v=v, e=e)
        vc = lines.colors_like("red", lines.v)
        assert vc.shape == (4, 3)
        np.testing.assert_allclose(vc, np.tile([1.0, 0.0, 0.0], (4, 1)))
        path = str(tmp_path / "l.obj")
        lines.write_obj(path)
        body = open(path).read()
        assert body.count("v ") == 4 and body.count("l ") == 3


class TestServerProcess:
    """The one process boundary in the system (SURVEY.md P4): fork the real
    server and check the dynamic-port handshake, headless-safe."""

    def _spawn(self, *args):
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        return subprocess.Popen(
            [sys.executable, "-m", "mesh_tpu.viewer.server"] + list(args),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env,
        )

    def test_port_handshake(self):
        proc = self._spawn("T", "1", "1", "64", "64")
        try:
            line = proc.stdout.readline()
            m = re.match(r"<PORT>(\d+)</PORT>", line)
            assert m, "no handshake line, got %r" % line
            assert 1023 < int(m.group(1)) < 65536
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_opengl_probe_reports(self):
        proc = self._spawn("TEST_FOR_OPENGL")
        out, _ = proc.communicate(timeout=30)
        assert out.startswith("success") or out.startswith("failure")


class TestProtocolDispatch:
    """Drive MeshViewerRemote.handle_request directly (no GL, no GLUT): the
    ZMQ message protocol must mutate subwindow state and serve queued events
    (reference meshviewer.py:1150-1203)."""

    def _remote(self):
        import zmq

        from mesh_tpu.viewer.server import MeshViewerRemote, Subwindow

        r = MeshViewerRemote.__new__(MeshViewerRemote)
        r.shape = (1, 2)
        r.subwindows = [[Subwindow() for _ in range(2)]]
        r.need_redraw = False
        r.keypress_queue = []
        r.mouseclick_queue = []
        r.pending_keypress_port = None
        r.pending_mouseclick_port = None
        r.pending_event_port = None
        r.width, r.height = 640, 480
        r.context = zmq.Context.instance()
        return r

    def test_state_labels(self):
        from mesh_tpu import Mesh
        from .fixtures import box

        r = self._remote()
        v, f = box()
        m = Mesh(v=v, f=f)
        r.handle_request({"label": "dynamic_meshes", "obj": [m],
                          "which_window": (0, 1)})
        assert r.subwindows[0][1].dynamic_meshes == [m]
        assert r.subwindows[0][0].dynamic_meshes == []
        assert r.need_redraw

        r.handle_request({"label": "background_color", "obj": [0, 0, 0],
                          "which_window": (0, 0)})
        np.testing.assert_array_equal(
            r.subwindows[0][0].background_color, [0, 0, 0]
        )
        r.handle_request({"label": "lighting_on", "obj": False,
                          "which_window": (0, 0)})
        assert r.subwindows[0][0].lighting_on is False

    def test_keypress_queue_replies_over_zmq(self):
        import zmq

        r = self._remote()
        # client side: bind a PULL socket the way _send_pyobj's blocking
        # path does, then ask for a keypress before and after the event
        pull = r.context.socket(zmq.PULL)
        port = pull.bind_to_random_port("tcp://127.0.0.1")
        try:
            r.handle_request({"label": "get_keypress", "port": port})
            assert r.pending_keypress_port == port  # queued, nothing yet
            r.on_keypress(b"a", 0, 0)
            msg = pull.recv_pyobj()  # flushed on the event
            assert msg == {"event_type": "keyboard", "key": "a"}
            assert r.pending_keypress_port is None
        finally:
            pull.close()

    def test_get_event_answers_on_next_keypress(self):
        import zmq

        r = self._remote()
        pull = r.context.socket(zmq.PULL)
        port = pull.bind_to_random_port("tcp://127.0.0.1")
        try:
            r.handle_request({"label": "get_event", "port": port})
            assert r.pending_event_port == port
            r.on_keypress(b"x", 0, 0)
            msg = pull.recv_pyobj()
            assert msg == {"event_type": "keyboard", "key": "x"}
            assert r.pending_event_port is None
        finally:
            pull.close()

    def test_get_event_drains_already_queued_event(self):
        import zmq

        r = self._remote()
        r.on_keypress(b"q", 0, 0)  # event fires BEFORE anyone asks
        pull = r.context.socket(zmq.PULL)
        port = pull.bind_to_random_port("tcp://127.0.0.1")
        try:
            r.handle_request({"label": "get_event", "port": port})
            msg = pull.recv_pyobj()  # served immediately, no second event
            assert msg == {"event_type": "keyboard", "key": "q"}
            assert r.pending_event_port is None
        finally:
            pull.close()

    def test_event_waiter_does_not_steal_from_keypress_waiter(self):
        import zmq

        r = self._remote()
        pull_a = r.context.socket(zmq.PULL)
        port_a = pull_a.bind_to_random_port("tcp://127.0.0.1")
        pull_b = r.context.socket(zmq.PULL)
        port_b = pull_b.bind_to_random_port("tcp://127.0.0.1")
        try:
            r.handle_request({"label": "get_keypress", "port": port_a})
            r.handle_request({"label": "get_event", "port": port_b})
            r.on_keypress(b"1", 0, 0)
            assert pull_a.recv_pyobj()["key"] == "1"  # dedicated waiter wins
            assert r.pending_keypress_port is None
            assert r.pending_event_port == port_b     # still waiting
            r.on_keypress(b"2", 0, 0)
            assert pull_b.recv_pyobj()["key"] == "2"
        finally:
            pull_a.close()
            pull_b.close()

    def test_get_window_shape_replies_immediately(self):
        import zmq

        r = self._remote()
        pull = r.context.socket(zmq.PULL)
        port = pull.bind_to_random_port("tcp://127.0.0.1")
        try:
            r.handle_request({"label": "get_window_shape", "port": port})
            msg = pull.recv_pyobj()
            assert msg["event_type"] == "window_shape"
            # reference contract: the SUBWINDOW GRID, not pixel dimensions
            # (reference meshviewer.py:949, 1146-1147)
            assert msg["shape"] == r.shape == (1, 2)
        finally:
            pull.close()

    def test_get_window_size_replies_pixels(self):
        import zmq

        r = self._remote()
        pull = r.context.socket(zmq.PULL)
        port = pull.bind_to_random_port("tcp://127.0.0.1")
        try:
            r.handle_request({"label": "get_window_size", "port": port})
            msg = pull.recv_pyobj()
            assert msg["event_type"] == "window_size"
            assert msg["size"] == (r.width, r.height)
        finally:
            pull.close()

    def test_dynamic_models_label_sets_meshes(self):
        r = self._remote()
        r.handle_request({"label": "dynamic_models", "obj": ["fake"],
                          "which_window": (0, 0)})
        assert r.subwindows[0][0].dynamic_meshes == ["fake"]


class TestCliRemote:
    """`meshviewer view/snap --port` talk the reference wire protocol to a
    server started with `meshviewer open -p` (reference bin/meshviewer:
    view/snap dispatch).  A bare PULL socket stands in for the server."""

    def _run_cli(self, argv):
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        return subprocess.run(
            [sys.executable, os.path.join(repo, "bin", "meshviewer")] + argv,
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "PYTHONPATH": repo},
        )

    def test_view_remote_sends_sanitized_meshes(self, tmp_path):
        import threading
        import zmq

        from mesh_tpu import Mesh
        from tests.fixtures import box

        v, f = box()
        path = str(tmp_path / "box.ply")
        Mesh(v=v, f=f).write_ply(path)

        ctx = zmq.Context.instance()
        server = ctx.socket(zmq.PULL)
        port = server.bind_to_random_port("tcp://127.0.0.1")
        got = {}

        def serve():
            msg = server.recv_pyobj()
            got.update(msg)
            if msg.get("port"):  # ack like the real server does
                push = ctx.socket(zmq.PUSH)
                push.connect("tcp://127.0.0.1:%d" % msg["port"])
                push.send_pyobj(0.0)
                push.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        res = self._run_cli([
            "view", path, "--port", str(port), "-ix", "1", "-iy", "0",
            "--timeout", "0",
        ])
        t.join(timeout=30)
        assert res.returncode == 0, res.stderr
        assert got["label"] == "dynamic_meshes"
        assert got["which_window"] == (0, 1)
        assert len(got["obj"]) == 1
        np.testing.assert_allclose(got["obj"][0].v, v, atol=1e-6)

    def test_snap_remote_requests_snapshot(self, tmp_path):
        import threading
        import zmq

        ctx = zmq.Context.instance()
        server = ctx.socket(zmq.PULL)
        port = server.bind_to_random_port("tcp://127.0.0.1")
        got = {}

        def serve():
            msg = server.recv_pyobj()
            got.update(msg)
            if msg.get("port"):
                push = ctx.socket(zmq.PUSH)
                push.connect("tcp://127.0.0.1:%d" % msg["port"])
                push.send_pyobj(0.0)
                push.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        out = str(tmp_path / "snap.png")
        res = self._run_cli(["snap", out, "--port", str(port)])
        t.join(timeout=30)
        assert res.returncode == 0, res.stderr
        assert got["label"] == "save_snapshot"
        assert got["obj"] == out


class TestTexturesAndLabels:
    """Texture rendering + vertex text labels, headless-testable parts:
    wedge-expansion arrays, texture image resolution, the set_texture
    protocol label, the reference mouse-click event schema, and the PIL
    text-image renderer behind GL label textures
    (reference meshviewer.py:381-388, 390-513; fonts.py:22-47)."""

    def _textured_box(self):
        from mesh_tpu import Mesh
        from .fixtures import box

        v, f = box()
        m = Mesh(v=v, f=f)
        # two uv islands sharing mesh vertices: forces wedge expansion
        m.vt = np.array([[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]])
        m.ft = np.tile(np.array([[0, 1, 2]]), (len(m.f), 1)).astype(np.uint32)
        return m

    def test_textured_arrays_wedge_expansion(self):
        from mesh_tpu.viewer.server import textured_arrays

        m = self._textured_box()
        positions, normals, uv, colors = textured_arrays(m)
        n_corners = m.f.size
        assert positions.shape == (n_corners, 3)
        assert normals.shape == (n_corners, 3)
        assert uv.shape == (n_corners, 2)
        assert colors is None
        # positions are v gathered by f
        np.testing.assert_allclose(
            positions, m.v[m.f.astype(int)].reshape(-1, 3), atol=1e-6
        )
        # uv gathered by ft, with v flipped to GL bottom-left origin
        expected_uv = m.vt[m.ft.astype(int)].reshape(-1, 2)
        expected_uv = np.column_stack([expected_uv[:, 0], 1.0 - expected_uv[:, 1]])
        np.testing.assert_allclose(uv, expected_uv, atol=1e-6)

    def test_textured_arrays_none_without_uv(self):
        from mesh_tpu import Mesh
        from mesh_tpu.viewer.server import textured_arrays
        from .fixtures import box

        v, f = box()
        assert textured_arrays(Mesh(v=v, f=f)) is None

    def test_mesh_texture_image_prefers_shipped_pixels(self, tmp_path):
        from mesh_tpu.viewer.server import mesh_texture_image

        m = self._textured_box()
        assert mesh_texture_image(m) is None
        m._texture_image = np.full((4, 4, 3), 7, np.uint8)
        im = mesh_texture_image(m)
        assert im.shape == (4, 4, 3) and im.dtype == np.uint8

    def test_mesh_texture_image_loads_filepath(self, tmp_path):
        cv2 = pytest.importorskip("cv2")
        from mesh_tpu.viewer.server import mesh_texture_image

        path = str(tmp_path / "t.png")
        cv2.imwrite(path, np.full((8, 8, 3), 128, np.uint8))
        m = self._textured_box()
        m.texture_filepath = path
        im = mesh_texture_image(m)
        assert im is not None and im.shape == (8, 8, 3)

    def test_set_texture_label_attaches_to_dynamic_meshes(self):
        r = TestProtocolDispatch._remote(TestProtocolDispatch())
        m = self._textured_box()
        r.handle_request({"label": "dynamic_meshes", "obj": [m],
                          "which_window": (0, 0)})
        img = np.zeros((2, 2, 3), np.uint8)
        r.handle_request({"label": "set_texture", "obj": img,
                          "which_window": (0, 0)})
        assert r.subwindows[0][0].dynamic_meshes[0]._texture_image.shape == (2, 2, 3)
        r.handle_request({"label": "set_texture", "obj": "/some/path.png",
                          "which_window": (0, 0)})
        assert r.subwindows[0][0].dynamic_meshes[0].texture_filepath == "/some/path.png"

    def test_sanitize_ships_texture_attrs(self):
        from mesh_tpu.viewer.meshviewer import _sanitize_meshes

        m = self._textured_box()
        m.texture_filepath = "/x.png"
        m._texture_image = np.ones((2, 2, 3), np.uint8)
        m.v_to_text = {0: "hello"}
        out = _sanitize_meshes([m])[0]
        assert out.texture_filepath == "/x.png"
        assert out._texture_image.shape == (2, 2, 3)
        assert out.v_to_text == {0: "hello"}
        assert hasattr(out, "vt") and hasattr(out, "ft")

    def test_right_click_event_schema(self):
        import zmq

        r = TestProtocolDispatch._remote(TestProtocolDispatch())
        r.unproject = lambda x, y: np.array([1.0, 2.0, 3.0])
        pull = r.context.socket(zmq.PULL)
        port = pull.bind_to_random_port("tcp://127.0.0.1")
        try:
            r.handle_request({"label": "get_mouseclick", "port": port})
            # left press starts a drag, emits no event
            r.on_click(0, 0, 5, 5)
            assert not r.mouseclick_queue and r.subwindows[0][0].isdragging
            r.on_click(0, 1, 5, 5)
            # right press in subwindow (0, 1) of the 1x2 grid emits the event
            r.on_click(2, 0, 500, 100)
            msg = pull.recv_pyobj()
            assert msg["event_type"] == "mouse_click_rightbutton"
            assert msg["which_subwindow"] == (0, 1)
            # u/v are viewport-relative: u = 500 - 320 (subwindow width), v
            # measured from the bottom of the 480-high window
            assert msg["u"] == 500 - 320
            assert msg["v"] == 480 - 100
            assert (msg["x"], msg["y"], msg["z"]) == (1.0, 2.0, 3.0)
        finally:
            pull.close()

    def test_fonts_text_image(self):
        from mesh_tpu.viewer.fonts import get_image_with_text

        im = get_image_with_text("hi", fgcolor=(1, 0, 0), bgcolor=(1, 1, 1))
        assert im.ndim == 3 and im.shape[2] == 3
        # some pixels must differ from the background
        assert (im != 255).any()

    def test_bundled_font_is_pinned(self):
        # the package ships DejaVu Sans (+ license) and the label
        # renderer must pick THAT file, not a system lookup — rendered
        # labels are then reproducible across installs (VERDICT r4
        # missing #3: the reference bundles ressources/Arial.ttf)
        import os

        from mesh_tpu.viewer.fonts import FONT_PATH, _label_font

        assert os.path.isfile(FONT_PATH), FONT_PATH
        assert os.path.isfile(
            os.path.join(os.path.dirname(FONT_PATH),
                         "DejaVuSans-LICENSE.txt"))
        font = _label_font(48)
        assert getattr(font, "path", None) == FONT_PATH
        # a TrueType render at 48px must produce substantially more ink
        # than the 8px bitmap fallback would — catches a silent fallback
        from mesh_tpu.viewer.fonts import get_image_with_text

        im = get_image_with_text("Wq", fgcolor=(0, 0, 0), bgcolor=(1, 1, 1))
        assert im.shape[0] > 60 and (im != 255).any(axis=2).sum() > 400


def _egl_available():
    import ctypes.util

    return ctypes.util.find_library("EGL") is not None


@pytest.mark.skipif(not _egl_available(), reason="no EGL library")
class TestOffscreenRendering:
    """Real rendering through the EGL pbuffer path: the snapshot evidence
    for textured meshes and vertex text labels (VERDICT items 1-2: reference
    meshviewer.py:381-388, 390-513, fonts.py:50-87).  Each test runs in a
    fresh subprocess so PyOpenGL's platform choice (fixed at first import)
    cannot leak into or out of the test process."""

    def _run(self, body):
        env = dict(os.environ)
        env["PYOPENGL_PLATFORM"] = "egl"
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        res = subprocess.run(
            [sys.executable, "-c", body], env=env, capture_output=True,
            text=True, timeout=240,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        return res.stdout

    def test_plain_mesh_renders(self):
        out = self._run("""
import numpy as np
from mesh_tpu import Mesh
from mesh_tpu.sphere import Sphere
from mesh_tpu.viewer.offscreen import render_scene
m = Sphere(np.zeros(3), 1.0).to_mesh()
m.set_vertex_colors("red")
im = render_scene([m], width=160, height=120)
assert im.shape == (120, 160, 3)
assert (im[60, 80] == [255, 0, 0]).all(), im[60, 80]   # lit red sphere center
print("OK")
""")
        assert "OK" in out

    def test_textured_mesh_renders_texture_colors(self):
        out = self._run("""
import numpy as np
from mesh_tpu import Mesh
from mesh_tpu.viewer.offscreen import render_scene
v = np.array([[-1,-1,0],[1,-1,0],[1,1,0],[-1,1,0]], float)
f = np.array([[0,1,2],[0,2,3]], np.uint32)
m = Mesh(v=v, f=f)
m.vt = np.array([[0,0],[1,0],[1,1],[0,1]], float)
m.ft = f.copy()
tex = np.zeros((8,8,3), np.uint8)
tex[:4] = [0, 0, 255]     # BGR: top half red
tex[4:] = [0, 255, 0]     # bottom half green
m._texture_image = tex
im = render_scene([m], width=64, height=64, lighting_on=False)
# quad center ~rows 17..47; OBJ v=1 (texture top) maps to the upper rows
assert (im[24, 32] == [255, 0, 0]).all(), im[24, 32]
assert (im[40, 32] == [0, 255, 0]).all(), im[40, 32]
print("OK")
""")
        assert "OK" in out

    def test_meshviewer_single_draws_into_context(self):
        """The reference-compat MeshViewerSingle adapter renders a real
        frame: its own viewport from pct coordinates + the shared
        draw_scene path (reference meshviewer.py:291-365)."""
        out = self._run("""
import numpy as np
from mesh_tpu.sphere import Sphere
from mesh_tpu.viewer.offscreen import OffscreenContext
from mesh_tpu.viewer.server import MeshViewerSingle
from mesh_tpu.viewer.arcball import Matrix4fT
m = Sphere(np.zeros(3), 1.0).to_mesh()
m.set_vertex_colors("red")
with OffscreenContext(width=128, height=64):
    s = MeshViewerSingle(0.0, 0.0, 0.5, 1.0)   # left half of the window
    s.window_size = (128, 64)
    s._renderer.setup_gl_state()
    s.dynamic_meshes = [m]
    d = s.get_dimensions()
    assert d['subwindow_width'] == 64.0, d
    cam = s.on_draw(Matrix4fT(), want_camera=True)
    assert cam['viewport'] == [0, 0, 64, 64], cam['viewport']
    assert cam['projection_matrix'].shape == (4, 4)
    im = s._renderer.read_pixels()
assert (im[32, 32] == [255, 0, 0]).all(), im[32, 32]   # sphere in left half
assert not (im[32, 96] == [255, 0, 0]).all()           # right half untouched
print("OK")
""")
        assert "OK" in out

    def test_labeled_mesh_renders_label(self):
        out = self._run("""
import numpy as np
from mesh_tpu import Mesh
from mesh_tpu.viewer.offscreen import render_scene
v = np.array([[-1,-1,0],[1,-1,0],[1,1,0],[-1,1,0]], float)
f = np.array([[0,1,2],[0,2,3]], np.uint32)
plain = render_scene([Mesh(v=v, f=f)], width=128, height=128)
m = Mesh(v=v, f=f)
m.v_to_text = {2: "hello"}
labeled = render_scene([m], width=128, height=128)
assert (labeled != plain).any(), "label drew nothing"
print("OK")
""")
        assert "OK" in out

    def test_cli_view_snapshot_headless_fallback(self, tmp_path):
        import struct

        from mesh_tpu.sphere import Sphere
        import numpy as np

        ply = str(tmp_path / "s.ply")
        Sphere(np.zeros(3), 1.0).to_mesh().write_ply(ply)
        out = str(tmp_path / "snap.png")
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "bin", "meshviewer"),
             "view", ply, "--snapshot", out],
            env=env, capture_output=True, text=True, timeout=240,
        )
        if "no usable OpenGL" in res.stderr and not os.path.exists(out):
            pytest.skip("neither GLUT nor EGL offscreen available")
        assert os.path.exists(out), res.stdout + res.stderr
        with open(out, "rb") as fh:
            assert fh.read(8) == b"\x89PNG\r\n\x1a\n"

    def test_repeated_renders_reuse_no_stale_textures(self):
        # texture ids die with each offscreen context; the second render
        # must re-upload, not bind a stale id from the cleared context
        out = self._run("""
import numpy as np
from mesh_tpu import Mesh
from mesh_tpu.viewer.offscreen import render_scene
v = np.array([[-1,-1,0],[1,-1,0],[1,1,0],[-1,1,0]], float)
f = np.array([[0,1,2],[0,2,3]], np.uint32)
def textured():
    m = Mesh(v=v, f=f)
    m.vt = np.array([[0,0],[1,0],[1,1],[0,1]], float)
    m.ft = f.copy()
    m._texture_image = np.full((8,8,3), [0,0,255], np.uint8)
    return m
a = render_scene([textured()], width=64, height=64, lighting_on=False)
b = render_scene([textured()], width=64, height=64, lighting_on=False)
assert (a == b).all(), "second render differs (stale texture cache)"
assert (a[32, 32] == [255, 0, 0]).all(), a[32, 32]
print("OK")
""")
        assert "OK" in out

    def test_cli_grid_snapshot_headless(self, tmp_path):
        import numpy as np

        from mesh_tpu.sphere import Sphere

        ply = str(tmp_path / "s.ply")
        Sphere(np.zeros(3), 1.0).to_mesh().write_ply(ply)
        out = str(tmp_path / "grid.png")
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "bin", "meshviewer"),
             "view", ply, ply, "--nx", "1", "--ny", "2",
             "--snapshot", out],
            env=env, capture_output=True, text=True, timeout=240,
        )
        if "no usable OpenGL" in res.stderr and not os.path.exists(out):
            pytest.skip("neither GLUT nor EGL offscreen available")
        assert os.path.exists(out), res.stdout + res.stderr
        from PIL import Image

        a = np.asarray(Image.open(out))
        h, w = a.shape[:2]
        left = a[:, : w // 2]
        right = a[:, w // 2:]
        # one sphere per half of the 1x2 grid
        assert (left != left[0, 0]).any(axis=2).sum() > 1000
        assert (right != right[0, 0]).any(axis=2).sum() > 1000
