"""The runtime lock witness (mesh_tpu/utils/lockwitness.py) and its
cross-check against the static LOK graph.

Unit tests drive the wrapper and the shadow-stack state directly —
no global factory patching, so they cannot perturb other tests.  The
slow-marked hammer is the end-to-end loop the ISSUE asks for: a
subprocess imports mesh_tpu with ``MESH_TPU_LOCK_WITNESS=1``, drives
store ingest, the page cache, the accel build cache, and the ledger
writers from 8 threads, dumps the witnessed acquisition orders, and
``mesh-tpu lint --witness`` validates the dynamic log against the
static graph and doc/concurrency.md.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from mesh_tpu.analysis import engine
from mesh_tpu.analysis.rules.lok import validate_witness
from mesh_tpu.utils import lockwitness
from mesh_tpu.utils.lockwitness import _WitnessedLock

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    lockwitness.reset()
    yield
    lockwitness.reset()


def _wrapped(site, factory=threading.Lock):
    return _WitnessedLock(factory(), site)


def test_nested_acquire_records_one_edge_per_held_lock():
    a = _wrapped("mesh_tpu/a.py:1")
    b = _wrapped("mesh_tpu/b.py:2")
    c = _wrapped("mesh_tpu/c.py:3")
    with a:
        with b:
            with c:
                pass
    edges = lockwitness.edges()
    assert edges == {
        ("mesh_tpu/a.py:1", "mesh_tpu/b.py:2"): 1,
        ("mesh_tpu/a.py:1", "mesh_tpu/c.py:3"): 1,
        ("mesh_tpu/b.py:2", "mesh_tpu/c.py:3"): 1,
    }
    # counts accumulate; disjoint acquisitions add no edges
    with a:
        with b:
            pass
    with c:
        pass
    edges = lockwitness.edges()
    assert edges[("mesh_tpu/a.py:1", "mesh_tpu/b.py:2")] == 2
    assert len(edges) == 3


def test_reentrant_reacquire_is_not_an_ordering_fact():
    a = _wrapped("mesh_tpu/a.py:1", threading.RLock)
    b = _wrapped("mesh_tpu/b.py:2")
    with a:
        with b:
            with a:          # re-entrant: must NOT record b -> a
                pass
    assert lockwitness.edges() == {
        ("mesh_tpu/a.py:1", "mesh_tpu/b.py:2"): 1}
    # the shadow stack survived the nested release
    with a:
        with b:
            pass
    assert lockwitness.edges()[
        ("mesh_tpu/a.py:1", "mesh_tpu/b.py:2")] == 2


def test_edges_are_per_thread():
    a = _wrapped("mesh_tpu/a.py:1")
    b = _wrapped("mesh_tpu/b.py:2")

    def other():
        with b:
            pass

    with a:
        t = threading.Thread(target=other)
        t.start()
        t.join()
    # thread 2 held nothing of its own: no a -> b edge
    assert lockwitness.edges() == {}


def test_condition_protocol_passthrough():
    lock = _WitnessedLock(threading.RLock(), "mesh_tpu/a.py:1")
    cond = threading.Condition(lock)
    with cond:
        cond.notify_all()    # requires a working _is_owned
    assert lockwitness.edges() == {}


def test_dump_load_roundtrip(tmp_path):
    a = _wrapped("mesh_tpu/a.py:1")
    b = _wrapped("mesh_tpu/b.py:2")
    with a:
        with b:
            pass
    path = str(tmp_path / "wit.jsonl")
    lockwitness.dump(path)
    assert lockwitness.load(path) == [
        (("mesh_tpu/a.py", 1), ("mesh_tpu/b.py", 2), 1)]
    # site lines survive too (single-lock runs still prove coverage)
    with open(path, encoding="utf-8") as fh:
        records = [json.loads(line) for line in fh]
    assert {"site": ["mesh_tpu/a.py", 1]} in records


# -- validate_witness against a synthetic project ----------------------

def _project(tmp_path, doc=None):
    pkg = tmp_path / "mesh_tpu" / "store"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text(textwrap.dedent("""\
        import threading
        A_LOCK = threading.Lock()
        B_LOCK = threading.Lock()

        def f():
            with A_LOCK:
                with B_LOCK:
                    pass
        """))
    if doc is not None:
        (tmp_path / "doc").mkdir()
        (tmp_path / "doc" / "concurrency.md").write_text(doc)
    project, failures = engine.build_project(str(tmp_path))
    assert not failures
    return project


def test_witness_edge_matching_static_graph_validates(tmp_path):
    project = _project(tmp_path)
    result = validate_witness(project, [
        (("mesh_tpu/store/a.py", 2), ("mesh_tpu/store/a.py", 3), 5)])
    assert result["ok"]
    assert result["checked"] == 1
    assert result["dynamic_only"] == []    # static analysis saw it too


def test_witness_reversed_edge_closes_a_cycle(tmp_path):
    project = _project(tmp_path)
    result = validate_witness(project, [
        (("mesh_tpu/store/a.py", 3), ("mesh_tpu/store/a.py", 2), 1)])
    assert not result["ok"]
    assert any("cycle" in p for p in result["problems"])
    assert result["dynamic_only"]          # the AST never saw B -> A


def test_witness_edge_contradicting_declared_order(tmp_path):
    project = _project(tmp_path, doc=textwrap.dedent("""\
        # Canonical lock order
        1. `mesh_tpu/store/a.py:B_LOCK`
        2. `mesh_tpu/store/a.py:A_LOCK`
        """))
    result = validate_witness(project, [
        (("mesh_tpu/store/a.py", 2), ("mesh_tpu/store/a.py", 3), 1)])
    assert not result["ok"]
    assert any("canonical order" in p for p in result["problems"])


def test_witness_unknown_sites_are_reported_not_fatal(tmp_path):
    project = _project(tmp_path)
    result = validate_witness(project, [
        (("somewhere/else.py", 9), ("mesh_tpu/store/a.py", 2), 1)])
    assert result["ok"]
    assert result["checked"] == 0
    assert result["unknown_sites"] == ["somewhere/else.py:9"]


# -- the end-to-end hammer ---------------------------------------------

_HAMMER = """
import os, sys, tempfile, threading
import numpy as np

import mesh_tpu
from mesh_tpu.utils import lockwitness
assert lockwitness.installed(), "witness knob did not install"

from mesh_tpu.accel.build import get_index
from mesh_tpu.obs.ledger import get_ledger
from mesh_tpu.store import pages
from mesh_tpu.store.store import MeshStore

tmp = tempfile.mkdtemp(prefix="witness_hammer_")
store = MeshStore(os.path.join(tmp, "store"))

def mesh(seed):
    rng = np.random.default_rng(seed)
    v = rng.standard_normal((12, 3)).astype(np.float32)
    f = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8], [9, 10, 11]],
                 dtype=np.int32)
    return v, f

errors = []
barrier = threading.Barrier(8)

def worker(tid):
    try:
        barrier.wait(timeout=30)
        ledger = get_ledger()
        for i in range(6):
            v, f = mesh(100 + (tid * 6 + i) % 9)   # overlap -> dedupe races
            store.ingest(v, f)                     # store locks
            get_index(v, f, kind="bvh")            # accel build cache lock
            pages.get_page_cache()                 # page-cache locks
            pages.clear_page_cache()
            rec = ledger.open(backend="hammer")    # ledger + registry locks
            ledger.close(rec)
    except Exception as exc:                       # pragma: no cover
        errors.append("t%d: %r" % (tid, exc))

threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
for t in threads:
    t.start()
for t in threads:
    t.join(timeout=120)
assert not errors, errors
path = lockwitness.dump(sys.argv[1])
print("witness edges:", len(lockwitness.edges()))
"""


@pytest.mark.slow
def test_hammer_witnessed_orders_validate_against_static_graph(tmp_path):
    witness_path = str(tmp_path / "witness.jsonl")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MESH_TPU_LOCK_WITNESS": "1",
        "MESH_TPU_LOCK_WITNESS_FILE": witness_path,
        "MESH_TPU_OBS": "1",
        "MESH_TPU_LEDGER": "1",
    })
    proc = subprocess.run(
        [sys.executable, "-c", _HAMMER, witness_path],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    witnessed = lockwitness.load(witness_path)
    assert witnessed, "8 threads over 4 subsystems recorded no orders"

    # the closing of the loop: the dynamic log validates against the
    # static graph + doc/concurrency.md of the real tree
    proc = subprocess.run(
        [sys.executable, "-m", "mesh_tpu.cli", "lint", "--witness",
         witness_path],
        cwd=_REPO, env={k: v for k, v in env.items()
                        if not k.startswith("MESH_TPU_LOCK")},
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "witness:" in proc.stdout and "-> OK" in proc.stdout
