"""Exactness of the culled Pallas kernel (interpret mode, CPU).

The culled kernel must agree with the plain-JAX brute force
(query.closest_faces_and_points) on distances everywhere and on faces up to
exact-distance ties — the same bar the brute-force Pallas kernel meets
(reference semantics: spatialsearchmodule.cpp:129-218 returns an arbitrary
winner among ties too).
"""

import numpy as np
import pytest

from mesh_tpu.query import closest_faces_and_points
from mesh_tpu.query.pallas_culled import closest_point_pallas_culled
from tests.fixtures import icosphere


def _assert_matches(res, ref, pts, atol=1e-5, min_face_match=0.3):
    np.testing.assert_allclose(
        np.sqrt(np.asarray(res["sqdist"])),
        np.sqrt(np.asarray(ref["sqdist"])),
        atol=atol,
        rtol=1e-4,
    )
    # closest points agree wherever the winning face is not an exact tie
    # (fine tessellations tie constantly: any projection near a shared edge
    # is equidistant from both incident faces, and f32 summation order then
    # decides the argmin — the reference's CGAL tree is equally arbitrary
    # about tie winners, so distance parity is the correctness bar)
    same = np.asarray(res["face"]) == np.asarray(ref["face"])
    np.testing.assert_allclose(
        np.asarray(res["point"])[same],
        np.asarray(ref["point"])[same],
        atol=atol,
    )
    # CGAL part codes (0-6) must agree wherever the winning face agrees
    np.testing.assert_array_equal(
        np.asarray(res["part"])[same], np.asarray(ref["part"])[same]
    )
    assert same.mean() >= min_face_match  # sanity: winners mostly coincide


def test_culled_matches_bruteforce_sphere():
    v, f = icosphere(3)  # 642 v / 1280 f
    rng = np.random.RandomState(0)
    pts = rng.randn(500, 3).astype(np.float32) * 1.5
    res = closest_point_pallas_culled(
        v.astype(np.float32), f, pts, tile_q=64, tile_f=256, interpret=True
    )
    ref = closest_faces_and_points(v.astype(np.float32), f, pts)
    _assert_matches(res, ref, pts)


def test_culled_far_queries_all_skipped_tiles_still_exact():
    v, f = icosphere(2)
    rng = np.random.RandomState(1)
    # queries far from the mesh: most tiles are skipped via the seed bound
    pts = (rng.randn(130, 3) * 0.1 + np.array([50.0, 0, 0])).astype(np.float32)
    res = closest_point_pallas_culled(
        v.astype(np.float32), f, pts, tile_q=64, tile_f=128, interpret=True
    )
    ref = closest_faces_and_points(v.astype(np.float32), f, pts)
    # at distance ~50 every query projects onto a silhouette vertex/edge
    # shared by many exactly-tied faces; only distance parity is meaningful
    _assert_matches(res, ref, pts, min_face_match=0.0)


def test_culled_on_surface_queries():
    v, f = icosphere(3)
    rng = np.random.RandomState(2)
    # queries exactly on the surface (barycentric samples of random faces):
    # the regime where exact ties at shared edges/vertices are common
    fi = rng.randint(0, len(f), 300)
    w = rng.dirichlet(np.ones(3), 300).astype(np.float32)
    tri = v[f[fi]]
    pts = np.einsum("qk,qkd->qd", w, tri).astype(np.float32)
    res = closest_point_pallas_culled(
        v.astype(np.float32), f, pts, tile_q=64, tile_f=128, interpret=True
    )
    np.testing.assert_allclose(
        np.sqrt(np.asarray(res["sqdist"])), 0.0, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(res["point"]), pts, atol=1e-5
    )


def test_culled_batched():
    v, f = icosphere(2)  # 162 v / 320 f
    rng = np.random.RandomState(3)
    batch = 3
    vs = (
        v[None] * (1.0 + 0.3 * rng.rand(batch, 1, 1))
        + rng.randn(batch, 1, 3) * 0.2
    ).astype(np.float32)
    pts = rng.randn(batch, 100, 3).astype(np.float32)
    res = closest_point_pallas_culled(
        vs, f, pts, tile_q=32, tile_f=64, interpret=True
    )
    assert res["face"].shape == (batch, 100)
    for bi in range(batch):
        ref = closest_faces_and_points(vs[bi], f, pts[bi])
        np.testing.assert_allclose(
            np.sqrt(np.asarray(res["sqdist"][bi])),
            np.sqrt(np.asarray(ref["sqdist"])),
            atol=1e-5,
            rtol=1e-4,
        )


def test_culled_nonmultiple_sizes():
    # Q and F not multiples of the tile sizes exercise the edge padding
    v, f = icosphere(1)  # 42 v / 80 f
    rng = np.random.RandomState(4)
    pts = rng.randn(37, 3).astype(np.float32)
    res = closest_point_pallas_culled(
        v.astype(np.float32), f, pts, tile_q=16, tile_f=32, interpret=True
    )
    ref = closest_faces_and_points(v.astype(np.float32), f, pts)
    np.testing.assert_allclose(
        np.sqrt(np.asarray(res["sqdist"])),
        np.sqrt(np.asarray(ref["sqdist"])),
        atol=1e-6,
        rtol=1e-5,
    )


def test_culled_degenerate_faces_never_underreport():
    """Same grafted-pathology case as the brute-force kernel's test
    (test_pallas.py): a point triangle and a collinear sliver must fall
    through to vertex/edge regions inside the culled kernel's fast tile,
    and the sphere-bound pruning must stay exact around them."""
    rng = np.random.RandomState(3)
    v, f = icosphere(1)
    v = v.astype(np.float32)
    f = f.astype(np.int32)
    extra_v = np.array(
        [[0.0, 0.0, 10.0],
         [-1.0, 0.0, 10.0], [1.0, 0.0, 10.0], [3.0, 0.0, 10.0]],
        np.float32,
    )
    n0 = len(v)
    v = np.vstack([v, extra_v])
    f = np.vstack([
        f,
        [[n0, n0, n0], [n0 + 1, n0 + 2, n0 + 3]],
    ]).astype(np.int32)
    pts = np.vstack([
        (rng.randn(30, 3) * 0.8).astype(np.float32),
        [[0.0, 0.5, 10.0]],
        [[0.1, -0.2, 9.0]],
    ]).astype(np.float32)
    ref = closest_faces_and_points(v, f, pts)
    res = closest_point_pallas_culled(
        v, f, pts, tile_q=8, tile_f=16, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(res["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
    )
    # both far queries project onto the sliver segment [-1,0,10]..[3,0,10]
    for qi, expect in [(-2, 0.5 ** 2), (-1, 0.2 ** 2 + 1.0 ** 2)]:
        np.testing.assert_allclose(
            float(np.asarray(res["sqdist"])[qi]), expect, rtol=1e-5
        )


@pytest.mark.parametrize("tile_variant", ["fast", "safe"])
def test_culled_safe_variant_matches_bruteforce(tile_variant):
    """The safe tile inside the culled grid must meet the same bar the
    fast tile does: the cull certificates are tile-geometry only, so the
    variant can only change per-pair distances, never pruning."""
    v, f = icosphere(3)
    rng = np.random.RandomState(5)
    pts = rng.randn(400, 3).astype(np.float32) * 1.5
    res = closest_point_pallas_culled(
        v.astype(np.float32), f, pts, tile_q=64, tile_f=256,
        interpret=True, tile_variant=tile_variant,
    )
    ref = closest_faces_and_points(v.astype(np.float32), f, pts)
    _assert_matches(res, ref, pts)


def test_culled_safe_variant_sliver_mesh():
    """Sliver-heavy mesh: the safe tile's direct-corner fallback must keep
    the culled kernel exact with assume_nondegenerate=False, the exact
    regime MESH_TPU_SAFE_TILES exists for."""
    rng = np.random.RandomState(6)
    v, f = icosphere(1)
    v = v.astype(np.float32)
    f = f.astype(np.int32)
    extra_v = np.array(
        [[0.0, 0.0, 10.0],
         [-1.0, 0.0, 10.0], [1.0, 0.0, 10.0], [3.0, 0.0, 10.0]],
        np.float32,
    )
    n0 = len(v)
    v = np.vstack([v, extra_v])
    f = np.vstack([
        f,
        [[n0, n0, n0], [n0 + 1, n0 + 2, n0 + 3]],
    ]).astype(np.int32)
    pts = np.vstack([
        (rng.randn(30, 3) * 0.8).astype(np.float32),
        [[0.0, 0.5, 10.0]],
        [[0.1, -0.2, 9.0]],
    ]).astype(np.float32)
    ref = closest_faces_and_points(v, f, pts)
    res = closest_point_pallas_culled(
        v, f, pts, tile_q=8, tile_f=16, interpret=True,
        assume_nondegenerate=False, tile_variant="safe",
    )
    np.testing.assert_allclose(
        np.asarray(res["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
    )


def test_culled_safe_variant_batched():
    v, f = icosphere(2)
    rng = np.random.RandomState(7)
    vs = (
        v[None] * (1.0 + 0.3 * rng.rand(2, 1, 1))
        + rng.randn(2, 1, 3) * 0.2
    ).astype(np.float32)
    pts = rng.randn(2, 90, 3).astype(np.float32)
    res = closest_point_pallas_culled(
        vs, f, pts, tile_q=32, tile_f=64, interpret=True, tile_variant="safe"
    )
    for bi in range(2):
        ref = closest_faces_and_points(vs[bi], f, pts[bi])
        np.testing.assert_allclose(
            np.sqrt(np.asarray(res["sqdist"][bi])),
            np.sqrt(np.asarray(ref["sqdist"])),
            atol=1e-5,
            rtol=1e-4,
        )


def test_culled_rejects_unknown_variant():
    v, f = icosphere(1)
    pts = np.zeros((4, 3), np.float32)
    with pytest.raises(ValueError, match="tile_variant"):
        closest_point_pallas_culled(
            v.astype(np.float32), f, pts, interpret=True,
            tile_variant="mystery",
        )
