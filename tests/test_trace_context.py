"""End-to-end request identity (obs/context.py, doc/observability.md).

The acceptance chain the ISSUE pins, chip-free:

- a minted RequestContext rides the serving tier into ledger meta, and
  spans opened on the engine executor's worker thread parent under the
  request's root span (ONE connected tree across the coalesce/drain
  thread hop, not a per-thread forest);
- tail sampling retains the full span tree for every deadline-miss /
  error / spilled request, drops plain ``ok`` ones, and keeps a bounded
  reservoir of the slowest ``ok`` closes;
- the serve latency histogram carries request_id *exemplars* (identity
  never becomes a label value — meshlint OBS006);
- flight-recorder incidents embed the retained tail (schema v4
  ``requests``) and ``mesh-tpu prof trace`` joins row + tree by id;
- ``MESH_TPU_TRACE_CONTEXT=0`` is bit-identical to the identity-free
  path: no request_id anywhere.
"""

import json

import numpy as np
import pytest

from mesh_tpu import engine, obs
from mesh_tpu.errors import DeadlineExceeded
from mesh_tpu.mesh import Mesh
from mesh_tpu.obs import prof
from mesh_tpu.obs.context import TraceTail, bind_context, mint
from mesh_tpu.obs.recorder import SCHEMA_VERSION, FlightRecorder
from mesh_tpu.obs.trace import span as obs_span
from mesh_tpu.serve import HealthMonitor, QueryService, Rung, ServeResult
from mesh_tpu.sphere import _icosphere


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    monkeypatch.setenv("MESH_TPU_OBS", "1")
    for var in ("MESH_TPU_TRACE_CONTEXT", "MESH_TPU_TRACE_TAIL",
                "MESH_TPU_TRACE_RESERVOIR", "MESH_TPU_LEDGER",
                "MESH_TPU_RECORDER"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("MESH_TPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    obs.reset()
    yield
    obs.reset()


def _answer(rung_name):
    return ServeResult(np.zeros((1, 4), np.uint32),
                       np.zeros((4, 3), np.float64), rung_name,
                       certified=True)


def _rung(name="ok", error=None):
    def fn(mesh, points, chunk, timeout):
        if error is not None:
            raise error("%s rung" % name)
        return _answer(name)
    return Rung(name, fn)


def _service(**kw):
    kw.setdefault("health", HealthMonitor(watchdog=False))
    kw.setdefault("workers", 1)
    kw.setdefault("ladder", [_rung()])
    return QueryService(**kw)


_MESH = object()
_PTS = np.zeros((4, 3), np.float32)


def _roots(spans):
    ids = {s["span_id"] for s in spans}
    return [s for s in spans if s.get("parent_id") not in ids]


# ---------------------------------------------------------------------------
# minting + kill switch


def test_mint_is_deterministic_and_killable(monkeypatch):
    a = mint("tenant-a", 3, 12.5, routing_key="k", replica="r0")
    b = mint("tenant-a", 3, 12.5)
    assert a.request_id == b.request_id       # same admission -> same id
    assert a.request_id.startswith("req-") and len(a.request_id) == 12
    assert mint("tenant-a", 4, 12.5).request_id != a.request_id
    meta = a.to_meta()
    assert meta["request_id"] == a.request_id
    assert meta["routing_key"] == "k" and meta["replica"] == "r0"
    assert "spilled" not in meta              # only stamped on the hop
    monkeypatch.setenv("MESH_TPU_TRACE_CONTEXT", "0")
    assert mint("tenant-a", 3, 12.5) is None


# ---------------------------------------------------------------------------
# satellite: span parent linkage across the executor thread hop


def test_executor_hop_yields_single_root_tree(monkeypatch):
    monkeypatch.delenv("MESH_TPU_NO_ENGINE", raising=False)
    obs.reset()
    rng = np.random.RandomState(3)
    v, f = _icosphere(2)
    meshes = [Mesh(v=v + 0.01 * rng.randn(*v.shape), f=f)
              for _ in range(2)]
    ptss = [np.asarray(rng.randn(q, 3), np.float32) for q in (50, 70)]
    ctx = mint("hop-tenant", 1, 10.0)
    record = obs.get_ledger().open(tenant="hop-tenant", **ctx.to_meta())
    record.ctx = ctx
    ex = engine.get_executor()
    with bind_context(ctx), \
            obs_span("serve.request", tenant="hop-tenant") as sp:
        ctx.root_span_id = sp.span_id
        with ex.coalesce():
            futs = [ex.submit("closest_point", m, p, record=record)
                    for m, p in zip(meshes, ptss)]
        ex.drain()
        for fut in futs:
            fut.result(timeout=60)
    obs.get_ledger().close(record, outcome="error")   # retain the tree
    entry = obs.get_trace_tail().lookup(ctx.request_id)
    assert entry is not None and entry["retained"] == "tail"
    spans = entry["spans"]
    names = {s["name"] for s in spans}
    assert {"serve.request", "engine.enqueue", "engine.coalesce"} <= names
    # the dispatch really crossed a thread: worker-side spans ran on a
    # different thread than the caller-side root
    assert len({s["thread"] for s in spans}) >= 2
    # ...and still form ONE connected tree rooted at serve.request
    roots = _roots(spans)
    assert len(roots) == 1 and roots[0]["name"] == "serve.request"
    assert all(s["attrs"].get("request_id") == ctx.request_id
               for s in spans)


# ---------------------------------------------------------------------------
# tail sampling: retention policy


def test_serve_tail_retains_miss_and_error_not_ok(monkeypatch):
    monkeypatch.setenv("MESH_TPU_TRACE_RESERVOIR", "0")
    obs.reset()
    svc = _service(ladder=[_rung("miss", DeadlineExceeded)],
                   default_deadline_s=5.0)
    try:
        fut = svc.submit(_MESH, _PTS, tenant="misser")
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)
    finally:
        svc.stop(write_stats=False)
    # a store-keyed request whose digest never resolves errors before
    # the ladder — the "error" close path
    svc = _service()
    try:
        fut = svc.submit("no-such-digest", _PTS, tenant="failer")
        with pytest.raises(Exception):
            fut.result(timeout=30)
        svc.submit(_MESH, _PTS, tenant="fine").result(timeout=30)
    finally:
        svc.stop(write_stats=False)
    entries = obs.get_trace_tail().retained()
    by_outcome = {e["outcome"]: e for e in entries}
    assert set(by_outcome) == {"deadline", "error"}   # ok not retained
    for entry in entries:
        assert entry["retained"] == "tail"
        assert entry["row"]["request_id"] == entry["request_id"]
    # the ladder-failing request kept its full connected span tree
    miss = by_outcome["deadline"]
    assert miss["spans"], "retained request kept no span tree"
    assert len(_roots(miss["spans"])) == 1
    # the ledger rows carry the same join keys
    rows = {r["tenant"]: r for r in obs.get_ledger().records()}
    assert rows["misser"]["request_id"] == miss["request_id"]


def test_tail_policy_spill_reservoir_and_ring_bound(monkeypatch):
    monkeypatch.setenv("MESH_TPU_TRACE_TAIL", "4")
    monkeypatch.setenv("MESH_TPU_TRACE_RESERVOIR", "2")
    tail = TraceTail()

    def close(rid, outcome="ok", total=1.0, **extra):
        tail.record_span({"name": "s", "span_id": 1, "parent_id": None,
                          "attrs": {"request_id": rid}})
        row = dict(request_id=rid, outcome=outcome, total_s=total, **extra)
        tail.observe_close(row)

    # a spilled ok request is tail-retained (the router hop is evidence)
    close("req-spill", outcome="ok", spilled=True)
    assert tail.lookup("req-spill")["retained"] == "tail"
    # the slow-ok reservoir keeps the 2 slowest, evicting the fastest
    close("req-s1", total=1.0)
    close("req-s2", total=3.0)
    close("req-s3", total=2.0)          # evicts req-s1 (1.0 < 2.0)
    assert tail.lookup("req-s1") is None
    assert tail.lookup("req-s2")["retained"] == "reservoir"
    assert tail.lookup("req-s3")["retained"] == "reservoir"
    close("req-fast", total=0.1)        # too fast for the reservoir
    assert tail.lookup("req-fast") is None
    # the ring is bounded: a storm of misses ages out the oldest
    for i in range(6):
        close("req-m%d" % i, outcome="deadline")
    assert len(tail.retained()) == 4
    assert tail.lookup("req-spill") is None


# ---------------------------------------------------------------------------
# exemplars: the histogram names the slowest request per bucket


def test_latency_histogram_carries_request_id_exemplars():
    obs.reset()
    svc = _service()
    try:
        svc.submit(_MESH, _PTS, tenant="ex").result(timeout=30)
    finally:
        svc.stop(write_stats=False)
    row = obs.get_ledger().records()[-1]
    snap = obs.REGISTRY.get("mesh_tpu_serve_latency_seconds").snapshot()
    exemplars = [e for series in snap["series"]
                 for e in series.get("exemplars", ())]
    assert exemplars, "latency histogram kept no exemplars"
    assert row["request_id"] in {e["request_id"] for e in exemplars}
    # stage histogram too (close() observes with the record's id)
    snap = obs.REGISTRY.get("mesh_tpu_request_stage_seconds").snapshot()
    stage_ex = [e for series in snap["series"]
                for e in series.get("exemplars", ())]
    assert row["request_id"] in {e["request_id"] for e in stage_ex}


# ---------------------------------------------------------------------------
# kill switch: identity-free path is bit-identical


def test_kill_switch_removes_identity_everywhere(monkeypatch):
    monkeypatch.setenv("MESH_TPU_TRACE_CONTEXT", "0")
    obs.reset()
    svc = _service(ladder=[_rung("miss", DeadlineExceeded)],
                   default_deadline_s=5.0)
    try:
        fut = svc.submit(_MESH, _PTS, tenant="dark")
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)
    finally:
        svc.stop(write_stats=False)
    row = obs.get_ledger().records()[-1]
    assert "request_id" not in row and "seq" not in row
    assert obs.get_trace_tail().retained() == []
    snap = obs.REGISTRY.get("mesh_tpu_serve_latency_seconds").snapshot()
    assert not any(series.get("exemplars")
                   for series in snap["series"])


# ---------------------------------------------------------------------------
# incidents embed the tail (schema v4) + prof joins by request_id


def test_incident_embeds_requests_tail_and_prof_joins(tmp_path):
    obs.reset()
    svc = _service(ladder=[_rung("miss", DeadlineExceeded)],
                   default_deadline_s=5.0)
    try:
        fut = svc.submit(_MESH, _PTS, tenant="victim")
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)
    finally:
        svc.stop(write_stats=False)
    rid = obs.get_trace_tail().retained()[-1]["request_id"]
    rec = FlightRecorder(capacity=8)
    path = rec.trigger("trace_tail_test")
    with open(path) as fh:
        incident = json.load(fh)
    assert incident["schema_version"] == SCHEMA_VERSION >= 4
    assert [e["request_id"] for e in incident["requests"]] == [rid]
    assert incident["requests"][0]["spans"]
    # prof joins the incident file's row + tree by id...
    trace = prof.request_trace(rid, paths=[path])
    assert trace["retained"] == "tail"
    assert [r["tenant"] for r in trace["rows"]] == ["victim"]
    assert trace["spans"] and len(_roots(trace["spans"])) == 1
    rendered = "\n".join(prof.render_request_trace(trace))
    assert rid in rendered and "victim" in rendered
    # ...and from a plain ledger JSONL dump + the live tail
    dump = tmp_path / "ledger.jsonl"
    obs.get_ledger().dump_jsonl(str(dump))
    trace = prof.request_trace(rid, paths=[str(dump)],
                               tail=obs.get_trace_tail())
    assert trace["rows"] and trace["spans"]
    with pytest.raises(prof.ProfError):
        prof.request_trace("req-ffffffff", paths=[path])
