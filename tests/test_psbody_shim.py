"""The `psbody.mesh` drop-in shim: code written against the reference
package must run unchanged (reference package layout: mesh/__init__.py,
psbody-mesh-namespace/__init__.py).

Each test is written in the reference's own idiom — same import paths, same
call shapes — so passing means a reference user can switch backends without
touching their code.
"""

import numpy as np
import pytest


class TestReferenceIdioms:
    def test_package_root_surface(self):
        from psbody.mesh import Mesh, MeshViewer, MeshViewers, texture_path

        assert callable(MeshViewer) and callable(MeshViewers)
        assert isinstance(texture_path, str)
        m = Mesh(v=np.eye(3), f=np.array([[0, 1, 2]], np.uint32))
        assert m.v.shape == (3, 3)

    def test_aabb_golden_through_shim(self):
        """The reference's own AABB test body, imports unchanged
        (reference tests/test_mesh.py:89-109)."""
        from psbody.mesh.mesh import Mesh

        from .test_reference_goldens import (
            AABB_F_SRC, AABB_FACES_EXPECTED, AABB_QUERIES, AABB_V_SRC,
        )

        m = Mesh(v=AABB_V_SRC, f=AABB_F_SRC)
        t = m.compute_aabb_tree()
        f_est, v_est = t.nearest(AABB_QUERIES)
        np.testing.assert_array_equal(
            np.asarray(f_est).ravel(), AABB_FACES_EXPECTED
        )

    def test_flat_geometry_api(self):
        """Chumpy-era flattened arrays (reference geometry modules)."""
        from psbody.mesh.geometry.tri_normals import TriNormals
        from psbody.mesh.geometry.vert_normals import VertNormals

        rng = np.random.RandomState(0)
        v = rng.randn(10, 3)
        f = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]], np.uint32)
        tn = np.asarray(TriNormals(v, f))
        assert tn.shape == (f.size,)            # flattened, one xyz per face
        vn = np.asarray(VertNormals(v, f))
        assert vn.shape == (v.size,)

    def test_serialization_roundtrip(self, tmp_path):
        from psbody.mesh import Mesh
        from psbody.mesh.serialization.serialization import write_ply

        m = Mesh(v=np.eye(3), f=np.array([[0, 1, 2]], np.uint32))
        path = str(tmp_path / "t.ply")
        write_ply(m, path)
        m2 = Mesh(filename=path)
        np.testing.assert_allclose(m2.v, m.v, atol=1e-6)

    def test_topology_and_search(self):
        from psbody.mesh.search import AabbNormalsTree, ClosestPointTree
        from psbody.mesh.sphere import Sphere
        from psbody.mesh.topology.connectivity import get_vert_connectivity
        from psbody.mesh.topology.subdivision import loop_subdivider

        m = Sphere(np.zeros(3), 1.0).to_mesh()
        conn = get_vert_connectivity(m)
        assert conn.shape == (len(m.v), len(m.v))
        up = loop_subdivider(m)
        hi = up(m)
        assert len(hi.v) > len(m.v)
        idx, dist = ClosestPointTree(m).nearest(np.zeros((2, 3)))
        assert len(np.asarray(idx)) == 2
        assert AabbNormalsTree(m) is not None

    def test_visibility_module(self):
        from psbody.mesh.sphere import Sphere
        from psbody.mesh.visibility import visibility_compute

        m = Sphere(np.zeros(3), 1.0).to_mesh()
        n = m.estimate_vertex_normals()
        vis, ndc = visibility_compute(
            v=m.v, f=m.f, cams=np.array([[0.0, 0.0, 3.0]]), n=n
        )
        vis = np.asarray(vis)
        assert vis.shape[-1] == len(m.v)
        front = np.asarray(m.v)[:, 2] > 0.5
        assert vis.reshape(-1)[front].all()

    def test_arcball_and_colors(self):
        from psbody.mesh.arcball import ArcBallT, Point2fT
        from psbody.mesh.colors import name_to_rgb

        ball = ArcBallT(640, 480)
        ball.click(Point2fT(300, 200))
        assert name_to_rgb["red"].shape == (3,)
