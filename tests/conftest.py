"""Test harness config: force an 8-device CPU JAX platform before jax loads,
so multi-device sharding tests run anywhere (SURVEY.md section 4: the
reference forks real viewer processes; we use XLA's host-platform device
virtualization for the device-level analog).

NOTE on this machine's TPU tunnel: an `axon` sitecustomize hook registers the
TPU PJRT plugin in every python process and overrides JAX_PLATFORMS=cpu.  It
only activates when PALLAS_AXON_POOL_IPS is set, so clearing that variable
(plus JAX_PLATFORMS=cpu) is what actually yields a CPU backend here.  Real-
TPU verification runs use the default environment instead (see
.claude/skills/verify/SKILL.md).
"""

import os

if os.environ.get("MESH_TPU_TEST_TPU"):
    # compiled-mode TPU run (`MESH_TPU_TEST_TPU=1 pytest -m tpu`): keep the
    # default backend — the real chip — instead of the virtual CPU platform
    pass
else:
    os.environ["PALLAS_AXON_POOL_IPS"] = ""     # disable the axon TPU hook
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_NUM_CPU_DEVICES", "8")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # The axon sitecustomize registers its plugin at interpreter start and
    # calls jax.config.update("jax_platforms", "axon,cpu"), overriding the
    # env var — counter-update the config here, before backend init.
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax (< 0.5) has no jax_num_cpu_devices option; the
        # XLA_FLAGS host-platform device count above covers it there
        pass

# per-test-session topology cache (reference Makefile:9-25 uses a throwaway
# PSBODY_MESH_CACHE for the same reason)
import tempfile

os.environ.setdefault("MESH_TPU_CACHE", tempfile.mkdtemp(prefix="mesh_tpu_cache_"))

# health trips auto-dump flight-recorder incidents (obs/recorder.py);
# route them to a throwaway dir so test-injected faults never pollute
# the operator's ~/.mesh_tpu/incidents
os.environ.setdefault(
    "MESH_TPU_INCIDENT_DIR", tempfile.mkdtemp(prefix="mesh_tpu_incidents_"))

# XLA's persistent compilation cache is content-keyed, so unlike the
# topology cache it is safe (and worth minutes per run) to share across
# test sessions; the throwaway MESH_TPU_CACHE above would defeat it
os.environ.setdefault(
    "MESH_TPU_XLA_CACHE",
    os.path.expanduser(os.path.join("~", ".mesh_tpu", "xla_test_cache")),
)
from mesh_tpu.utils.compilation_cache import (  # noqa: E402
    enable_persistent_compilation_cache,
)

enable_persistent_compilation_cache()
