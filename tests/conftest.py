"""Test harness config: force an 8-device CPU JAX platform before jax loads,
so multi-device sharding tests run anywhere (SURVEY.md section 4: the
reference forks real viewer processes; we use XLA's host-platform device
virtualization for the device-level analog)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# per-test-session topology cache (reference Makefile:9-25 uses a throwaway
# PSBODY_MESH_CACHE for the same reason)
import tempfile

os.environ.setdefault("MESH_TPU_CACHE", tempfile.mkdtemp(prefix="mesh_tpu_cache_"))
