"""One process of the two-host multihost test (tests/test_multihost.py).

Usage: python tests/_multihost_child.py <process_id> <coordinator_port>

Forces a 4-device CPU platform (so two processes form an 8-device global
mesh with Gloo collectives between them — the DCN stand-in), joins the
process group, runs the multihost closest-point query on its local shard
of the points, and checks the gathered result against the single-device
reference computed locally.  Prints MULTIHOST_OK on success.
"""

import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""     # disable the axon TPU hook
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = "4"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:
    pass                         # jax < 0.5: XLA_FLAGS above covers it

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import optax  # noqa: E402

from mesh_tpu.models import synthetic_body_model  # noqa: E402
from mesh_tpu.parallel import (  # noqa: E402
    global_device_mesh,
    init_fit_state,
    initialize_multihost,
    make_fit_step,
    multihost_closest_faces_and_points,
)
from mesh_tpu.query import closest_faces_and_points  # noqa: E402
from mesh_tpu.models import smpl_sized_sphere  # noqa: E402


def main():
    pid, port = int(sys.argv[1]), int(sys.argv[2])
    n_procs = 2
    live = initialize_multihost(
        coordinator_address="localhost:%d" % port,
        num_processes=n_procs, process_id=pid,
    )
    assert live and jax.process_count() == n_procs
    assert len(jax.devices()) == 8, jax.devices()

    # SMPL-template scale (6890 v / 13776 f) with >=10k scan points split
    # RAGGED across the two hosts (6000 + 4100, neither divisible by the 4
    # local devices): exercises the count exchange, per-process padding,
    # and per-block trim of the pod-scale facade (VERDICT r3 #6)
    v, f = smpl_sized_sphere()
    rng = np.random.RandomState(7)
    split = (6000, 4100)
    pts_global = rng.randn(sum(split), 3).astype(np.float32)
    local = (pts_global[:split[0]], pts_global[split[0]:])[pid]

    res = multihost_closest_faces_and_points(
        v.astype(np.float32), f.astype(np.int32), local
    )
    ref = closest_faces_and_points(
        v.astype(np.float32), f.astype(np.int32), pts_global
    )
    np.testing.assert_array_equal(res["face"], np.asarray(ref["face"]))
    np.testing.assert_allclose(
        res["point"], np.asarray(ref["point"]), atol=1e-5
    )
    np.testing.assert_allclose(
        res["sqdist"], np.asarray(ref["sqdist"]), atol=1e-5
    )

    # the training step runs SPMD across hosts unchanged: batch sharded
    # dp over both processes' devices, scan points dp x sp
    model = synthetic_body_model(
        seed=0, n_betas=4, n_joints=6,
        template=(v * np.array([0.3, 0.2, 0.9]), f),
    )
    mesh = global_device_mesh(("dp", "sp"), (4, 2))
    opt = optax.adam(1e-2)
    state, _ = init_fit_state(model, batch_size=8, optimizer=opt)
    step = make_fit_step(model, opt, mesh=mesh)
    target = np.random.RandomState(0).randn(8, 64, 3).astype(np.float32) * 0.3
    state, loss0 = step(state, target)
    for _ in range(3):
        state, loss = step(state, target)
    assert np.isfinite(float(loss)) and float(loss) < float(loss0)
    # the parent asserts both processes print the identical loss
    print("MULTIHOST_FIT_LOSS %.9f" % float(loss), flush=True)
    print("MULTIHOST_OK process=%d" % pid, flush=True)


if __name__ == "__main__":
    main()
