"""One process of the two-host multihost test (tests/test_multihost.py).

Usage: python tests/_multihost_child.py <process_id> <coordinator_port>

Forces a 4-device CPU platform (so two processes form an 8-device global
mesh with Gloo collectives between them — the DCN stand-in), joins the
process group, runs the multihost closest-point query on its local shard
of the points, and checks the gathered result against the single-device
reference computed locally.  Prints MULTIHOST_OK on success.
"""

import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""     # disable the axon TPU hook
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = "4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mesh_tpu.parallel import (  # noqa: E402
    initialize_multihost,
    multihost_closest_faces_and_points,
)


def main():
    pid, port = int(sys.argv[1]), int(sys.argv[2])
    n_procs = 2
    live = initialize_multihost(
        coordinator_address="localhost:%d" % port,
        num_processes=n_procs, process_id=pid,
    )
    assert live and jax.process_count() == n_procs
    assert len(jax.devices()) == 8, jax.devices()

    from mesh_tpu.query import closest_faces_and_points
    from mesh_tpu.sphere import _icosphere

    v, f = _icosphere(3)
    rng = np.random.RandomState(7)
    # 61 rows per process: NOT divisible by the 4 local devices, so the
    # facade's per-process padding (and its per-block trim) is exercised
    pts_global = rng.randn(122, 3).astype(np.float32)
    local = pts_global[pid * 61:(pid + 1) * 61]       # this host's shard

    res = multihost_closest_faces_and_points(
        v.astype(np.float32), f.astype(np.int32), local
    )
    ref = closest_faces_and_points(
        v.astype(np.float32), f.astype(np.int32), pts_global
    )
    np.testing.assert_array_equal(res["face"], np.asarray(ref["face"]))
    np.testing.assert_allclose(
        res["point"], np.asarray(ref["point"]), atol=1e-5
    )
    np.testing.assert_allclose(
        res["sqdist"], np.asarray(ref["sqdist"]), atol=1e-5
    )
    print("MULTIHOST_OK process=%d" % pid, flush=True)


if __name__ == "__main__":
    main()
