"""Pallas closest-point kernel correctness (interpret mode on the CPU test
platform; the same kernel runs compiled on TPU — see bench.py)."""

import numpy as np
import pytest

from mesh_tpu.query import closest_faces_and_points
from mesh_tpu.query.pallas_closest import closest_point_pallas

from .fixtures import box, icosphere


class TestPallasClosestPoint:
    @pytest.mark.parametrize("n_q", [16, 300])
    def test_matches_plain_jax(self, n_q):
        rng = np.random.RandomState(0)
        v, f = icosphere(1)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        q = (rng.randn(n_q, 3) * 0.8).astype(np.float32)
        ref = closest_faces_and_points(v, f, q)
        out = closest_point_pallas(v, f, q, tile_q=8, tile_f=128, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(out["point"]), np.asarray(ref["point"]), atol=1e-4
        )
        # parts agree wherever faces agree (ties can pick either neighbor)
        same = np.asarray(out["face"]) == np.asarray(ref["face"])
        assert same.mean() > 0.8
        np.testing.assert_array_equal(
            np.asarray(out["part"])[same], np.asarray(ref["part"])[same]
        )

    def test_part_codes(self):
        v, f = box(2.0)
        q = np.array([[0.3, 0.2, -5.0]], np.float32)
        out = closest_point_pallas(
            v.astype(np.float32), f.astype(np.int32), q,
            tile_q=8, tile_f=128, interpret=True,
        )
        assert int(np.asarray(out["part"])[0]) == 0
        np.testing.assert_allclose(
            np.asarray(out["point"]), [[0.3, 0.2, -1.0]], atol=1e-6
        )
