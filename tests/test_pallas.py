"""Pallas closest-point kernel correctness (interpret mode on the CPU test
platform; the same kernel runs compiled on TPU — see bench.py)."""

import numpy as np
import pytest

from mesh_tpu.query import closest_faces_and_points
from mesh_tpu.query.pallas_closest import closest_point_pallas

from .fixtures import box, icosphere
from mesh_tpu.utils.jax_compat import enable_x64


class TestPallasClosestPoint:
    @pytest.mark.parametrize("n_q", [16, 300])
    def test_matches_plain_jax(self, n_q):
        rng = np.random.RandomState(0)
        v, f = icosphere(1)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        q = (rng.randn(n_q, 3) * 0.8).astype(np.float32)
        ref = closest_faces_and_points(v, f, q)
        out = closest_point_pallas(v, f, q, tile_q=8, tile_f=128, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(out["point"]), np.asarray(ref["point"]), atol=1e-4
        )
        # parts agree wherever faces agree (ties can pick either neighbor)
        same = np.asarray(out["face"]) == np.asarray(ref["face"])
        assert same.mean() > 0.8
        np.testing.assert_array_equal(
            np.asarray(out["part"])[same], np.asarray(ref["part"])[same]
        )

    def test_part_codes(self):
        v, f = box(2.0)
        q = np.array([[0.3, 0.2, -5.0]], np.float32)
        out = closest_point_pallas(
            v.astype(np.float32), f.astype(np.int32), q,
            tile_q=8, tile_f=128, interpret=True,
        )
        assert int(np.asarray(out["part"])[0]) == 0
        np.testing.assert_allclose(
            np.asarray(out["point"]), [[0.3, 0.2, -1.0]], atol=1e-6
        )

    def test_nearest_vertices_matches_xla(self):
        from mesh_tpu.query.closest_point import _closest_vertices_xla
        from mesh_tpu.query.pallas_closest import nearest_vertices_pallas

        rng = np.random.RandomState(6)
        v, _ = icosphere(2)
        v = v.astype(np.float32)
        q = (rng.randn(300, 3) * 1.3).astype(np.float32)
        i_p, d_p = nearest_vertices_pallas(v, q, tile_q=32, tile_v=64,
                                           interpret=True)
        i_x, d_x = _closest_vertices_xla(v, q)
        np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_x),
                                   atol=1e-5)
        # index ties only at exactly equidistant vertices
        same = np.asarray(i_p) == np.asarray(i_x)
        assert same.mean() > 0.99

    def test_vmapped_batch_matches_per_mesh(self):
        """The bench composes the kernel under vmap (one launch for all B
        meshes); the lifted grid must agree with per-mesh calls."""
        import jax

        rng = np.random.RandomState(4)
        v, f = icosphere(1)
        f = f.astype(np.int32)
        batch_v = (v[None] + rng.randn(3, 1, 3) * 0.1).astype(np.float32)
        batch_q = (rng.randn(3, 50, 3) * 0.8).astype(np.float32)
        out = jax.vmap(
            lambda vv, qq: closest_point_pallas(
                vv, f, qq, tile_q=16, tile_f=32, interpret=True
            )["sqdist"]
        )(batch_v, batch_q)
        for b in range(3):
            ref = closest_point_pallas(
                batch_v[b], f, batch_q[b], tile_q=16, tile_f=32,
                interpret=True,
            )
            np.testing.assert_allclose(
                np.asarray(out[b]), np.asarray(ref["sqdist"]), atol=1e-6
            )

    def test_far_from_origin_conditioning(self):
        """The centering prologue must keep the corner-a derived terms
        (d3 = d1 - ab2 etc.) well-conditioned when the mesh sits far from
        the origin — raw f32 coordinates at offset 1e3 would lose ~7
        digits to cancellation without it."""
        rng = np.random.RandomState(5)
        v, f = icosphere(2)
        offset = np.array([1e3, -2e3, 5e2])
        v_far = (v + offset).astype(np.float32)
        f = f.astype(np.int32)
        q_far = ((rng.randn(100, 3) * 0.8) + offset).astype(np.float32)
        out = closest_point_pallas(v_far, f, q_far, tile_q=32, tile_f=128,
                                   interpret=True)
        # genuine f64 oracle: without enable_x64 jnp would silently
        # downcast and the oracle would share the f32 rounding under test
        import jax

        with enable_x64(True):
            ref = closest_faces_and_points(
                (v + offset).astype(np.float64), f,
                q_far.astype(np.float64),
            )
        np.testing.assert_allclose(
            np.sqrt(np.asarray(out["sqdist"])),
            np.sqrt(np.asarray(ref["sqdist"])),
            atol=1e-4,
        )

    def test_degenerate_faces_never_underreport(self):
        """Zero-area and collinear faces must fall through to their
        vertex/edge regions (zeroed reciprocals in _face_rows_fast), not
        report a bogus interior plane distance that steals the argmin."""
        rng = np.random.RandomState(3)
        v, f = icosphere(1)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        # graft pathological faces far from the sphere: a point triangle
        # (all three corners equal) and a collinear sliver, both at z=+10
        extra_v = np.array(
            [[0.0, 0.0, 10.0],                      # point triangle corner
             [-1.0, 0.0, 10.0], [1.0, 0.0, 10.0], [3.0, 0.0, 10.0]],
            np.float32,
        )
        n0 = len(v)
        v = np.vstack([v, extra_v])
        f = np.vstack([
            f,
            [[n0, n0, n0], [n0 + 1, n0 + 2, n0 + 3]],
        ]).astype(np.int32)
        q = np.vstack([
            (rng.randn(30, 3) * 0.8).astype(np.float32),   # near the sphere
            [[0.0, 0.5, 10.0]],    # closest to the sliver's interior span
            [[0.1, -0.2, 9.0]],    # closest to the point triangle
        ]).astype(np.float32)
        ref = closest_faces_and_points(v, f, q)
        out = closest_point_pallas(v, f, q, tile_q=8, tile_f=128,
                                   interpret=True)
        np.testing.assert_allclose(
            np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )
        # the sphere-adjacent queries must not be captured by the
        # degenerate faces
        assert np.all(np.asarray(out["face"])[:30] < len(f) - 2)
        # the far queries resolve to the grafted geometry at the exact
        # segment distance (the collinear sliver acts as the segment
        # [-1,0,10]..[3,0,10]; both queries project onto its interior)
        for qi, expect in [(-2, 0.5 ** 2), (-1, 0.2 ** 2 + 1.0 ** 2)]:
            np.testing.assert_allclose(
                float(np.asarray(out["sqdist"])[qi]), expect, rtol=1e-5
            )


class TestMxuTile:
    """MXU-fed tile (closest_point_pallas_mxu, production-routed past the
    MESH_TPU_MXU crossover — see tests/test_mxu.py): same contract
    as the production tile; face choice may differ only at exact-distance
    ties (the documented corner-derivation behavior)."""

    def test_matches_reference(self):
        from mesh_tpu.query.pallas_closest import closest_point_pallas_mxu

        rng = np.random.RandomState(5)
        v, f = icosphere(2)
        v = (v * np.array([0.3, 0.2, 0.9])).astype(np.float32)
        f = f.astype(np.int32)
        q = (rng.randn(500, 3) * 0.4).astype(np.float32)
        ref = closest_faces_and_points(v, f, q)
        out = closest_point_pallas_mxu(v, f, q, tile_q=64, tile_f=128,
                                       interpret=True)
        np.testing.assert_allclose(
            np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(out["point"]), np.asarray(ref["point"]), atol=1e-4
        )

    def test_disagreements_are_ties(self):
        from mesh_tpu.query.pallas_closest import (
            closest_point_pallas,
            closest_point_pallas_mxu,
        )
        from mesh_tpu.query.point_triangle import closest_point_on_triangle

        rng = np.random.RandomState(7)
        v, f = icosphere(2)
        v = v.astype(np.float32)
        f = f.astype(np.int32)
        q = (rng.randn(400, 3) * 0.8).astype(np.float32)
        a = closest_point_pallas_mxu(v, f, q, tile_q=64, tile_f=128,
                                     interpret=True)
        b = closest_point_pallas(v, f, q, tile_q=64, tile_f=128,
                                 interpret=True)
        fa, fb = np.asarray(a["face"]), np.asarray(b["face"])
        dis = np.nonzero(fa != fb)[0]
        if dis.size:
            tri = v[f]

            def exact(fi):
                t = tri[fi]
                _, sq, _ = closest_point_on_triangle(
                    q[dis], t[:, 0], t[:, 1], t[:, 2]
                )
                return np.asarray(sq)

            gap = np.abs(exact(fa[dis]) - exact(fb[dis]))
            assert gap.max() < 1e-6, gap.max()

    def test_degenerate_faces(self):
        from mesh_tpu.query.pallas_closest import closest_point_pallas_mxu

        rng = np.random.RandomState(9)
        v, f = icosphere(1)
        v = v.astype(np.float32)
        # append a duplicate-corner face and a collinear face
        v = np.vstack([v, v[:1] * 1.5, v[:1] * 2.0]).astype(np.float32)
        nv = len(v)
        f = np.vstack([f, [[0, nv - 2, nv - 2]], [[0, nv - 2, nv - 1]]])
        f = f.astype(np.int32)
        q = (rng.randn(200, 3) * 1.2).astype(np.float32)
        ref = closest_faces_and_points(v, f, q)
        out = closest_point_pallas_mxu(v, f, q, tile_q=64, tile_f=128,
                                       interpret=True)
        np.testing.assert_allclose(
            np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5
        )
