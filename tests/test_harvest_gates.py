"""tools/harvest_gates.py: gate-log harvesting and BASELINE.md stamping.

The watchdog (tools/tpu_watchdog.sh) depends on ``--write`` replacing the
delimited auto-harvest section idempotently and never touching the
hand-written rows around it.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import harvest_gates  # noqa: E402


def _make_logdir(tmp_path):
    d = tmp_path / "gates"
    d.mkdir()
    (d / "gate1.log").write_text(
        "....................\n20 passed in 93.21s\n")
    (d / "gate2.log").write_text(
        "device: TPU\n"
        + json.dumps({"metric": "batch256_smpl_normals_plus_closest_point",
                      "value": 1723427.0, "unit": "queries/sec",
                      "vs_baseline": 1125.0,
                      "device_absolute": {"pct_vpu_peak": 42.6}}) + "\n")
    (d / "config5.log").write_text(
        json.dumps({"metric": "config5_scan100k_closest_faces",
                    "value": 1324000.0, "unit": "queries/sec",
                    "vs_baseline": 120.0,
                    "device_absolute": {"pct_vpu_peak": 40.0}}) + "\n"
        + json.dumps({"suite": "baseline_configs", "results": []}) + "\n")
    (d / "sweep.log").write_text(
        json.dumps({"tile_q": 256, "tile_f": 2048,
                    "queries_per_sec": 1.7e6}) + "\n"
        + json.dumps({"best": {"tile_q": 256, "tile_f": 2048,
                               "queries_per_sec": 1.7e6},
                      "n_errors": 0}) + "\n")
    return str(d)


def test_harvest_collects_all_gates(tmp_path):
    h = harvest_gates.harvest(_make_logdir(tmp_path))
    assert "20 passed" in h["gate1"]["summary"]
    assert h["bench"]["value"] == 1723427.0
    assert [c["metric"] for c in h["configs"]] == [
        "config5_scan100k_closest_faces"]
    assert h["sweeps"][0]["best"]["tile_f"] == 2048
    table = harvest_gates.render_table(h)
    assert "config5_scan100k_closest_faces" in table
    assert "1723427.0" in table
    assert "device_absolute" in table


def test_write_baseline_is_idempotent_and_preserves_text(tmp_path):
    h = harvest_gates.harvest(_make_logdir(tmp_path))
    baseline = tmp_path / "BASELINE.md"
    hand_written = "# BASELINE\n\nhand-written analysis row\n"
    baseline.write_text(hand_written)

    harvest_gates.write_baseline(h, str(baseline))
    text1 = baseline.read_text()
    assert hand_written.strip() in text1
    assert text1.count(harvest_gates._BEGIN) == 1
    assert "config5_scan100k_closest_faces" in text1

    # restamp: section replaced, not duplicated; surrounding text intact
    harvest_gates.write_baseline(h, str(baseline))
    text2 = baseline.read_text()
    assert text2.count(harvest_gates._BEGIN) == 1
    assert text2.count("## Latest on-chip gate run") == 1
    assert hand_written.strip() in text2


def test_failed_captures_render_as_failures(tmp_path):
    # a wedged capture (value null + error) must read as a failure in the
    # stamped section, not as a meaningless "None None" row
    d = tmp_path / "gates"
    d.mkdir()
    (d / "gate2.log").write_text(json.dumps(
        {"metric": "m", "value": None, "unit": "queries/sec",
         "vs_baseline": None, "error": "jax backend probe failed"}) + "\n")
    (d / "config4.log").write_text(json.dumps(
        {"metric": "config4_hand_body_intersection",
         "error": "RESOURCE_EXHAUSTED: vmem"}) + "\n")
    table = harvest_gates.render_table(harvest_gates.harvest(str(d)))
    assert "CAPTURE FAILED" in table and "probe failed" in table
    assert "FAILED: RESOURCE_EXHAUSTED" in table
    assert "None None" not in table


def test_stale_bench_record_is_labelled(tmp_path):
    d = tmp_path / "gates"
    d.mkdir()
    (d / "gate2.log").write_text(json.dumps(
        {"metric": "m", "value": 5.0, "unit": "q/s", "vs_baseline": 2.0,
         "stale": True}) + "\n")
    h = harvest_gates.harvest(str(d))
    assert "STALE" in harvest_gates.render_table(h)


def test_gate2b_wedged_vs_cpu_fallback_are_distinct(tmp_path):
    # both records lack "kernel_knobs", but for different reasons: the
    # wedged attempt carries the stale default headline + the knobs it
    # WOULD have measured, while a live CPU-fallback run simply ignored
    # the knobs.  Neither may render as an A/B measurement, and the
    # CPU-fallback one must not claim the tunnel was wedged.
    d = tmp_path / "gates"
    d.mkdir()
    (d / "gate2b_safe.log").write_text(json.dumps(
        {"metric": "m", "value": 5.0, "unit": "q/s", "vs_baseline": 2.0,
         "stale": True,
         "kernel_knobs_requested": {"tile_variant": "safe",
                                    "reduction": "exact"}}) + "\n")
    (d / "gate2b_cpu.log").write_text(json.dumps(
        {"metric": "m", "value": 7.0, "unit": "q/s",
         "vs_baseline": 1.0}) + "\n")
    table = harvest_gates.render_table(harvest_gates.harvest(str(d)))
    assert "tunnel wedged" in table
    assert '"tile_variant": "safe"' in table
    assert "CPU fallback" in table
    assert "knobs ignored" in table
    # the CPU-fallback line carries its (default-path) value, labelled
    assert "7.0 q/s is a default-path measurement" in table


def test_gate2_mxu_row_grades_contract_not_just_speed(tmp_path):
    # the MXU row is a correctness gate first: drifted bit-identity
    # flags or a repair rate of 1.0 render as NOT AN IMPROVEMENT even
    # with a great speedup; only a clean record gets the OK line
    def _gate2(mxu):
        d = tmp_path / ("gates_%d" % _gate2.n)
        _gate2.n += 1
        d.mkdir()
        (d / "gate2.log").write_text(json.dumps(
            {"metric": "m", "value": 5.0, "unit": "q/s",
             "vs_baseline": 2.0, "mxu": mxu}) + "\n")
        return harvest_gates.render_table(harvest_gates.harvest(str(d)))

    _gate2.n = 0
    good = {"value": 1.879, "checksum": 587.1954, "repair_rate": 0.2344,
            "repaired": 15, "screened": 64, "dense_match": True,
            "degenerate_match": True, "leaf_visit_match": True}
    table = _gate2(good)
    assert "gate 2 mxu: 1.879x vpu/repair OK" in table

    table = _gate2(dict(good, degenerate_match=False))
    assert "NOT AN IMPROVEMENT" in table and "bit-identity flags" in table

    table = _gate2(dict(good, repair_rate=1.0))
    assert "NOT AN IMPROVEMENT" in table and "prunes nothing" in table

    table = _gate2(dict(good, checksum=None))
    assert ("NOT AN IMPROVEMENT" in table
            and "no speedup/checksum" in table)
