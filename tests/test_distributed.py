"""Multi-host bootstrap helpers (parallel/distributed.py) on the
single-host virtual 8-device platform."""

import numpy as np
import pytest

import jax

from mesh_tpu.parallel.distributed import (
    global_device_mesh,
    initialize_multihost,
)


def test_initialize_single_host_degrades_to_false():
    # no arguments on a single host: auto-detect failure (or an already-
    # initialized single-process group) must report "not multi-host"
    assert initialize_multihost() is False


def test_initialize_explicit_args_propagate_errors():
    # explicit arguments mean the caller intends multi-host, so jax's
    # error must propagate instead of degrading to single-process
    # operation: ValueError (process_id >= num_processes) on a fresh
    # process, RuntimeError (already initialized) when an earlier test's
    # auto-detect bootstrap ran first
    with pytest.raises((ValueError, RuntimeError)):
        initialize_multihost(
            coordinator_address="127.0.0.1:1", num_processes=2, process_id=5
        )


def test_global_device_mesh_1d_default():
    mesh = global_device_mesh()
    assert mesh.axis_names == ("dp",)
    assert mesh.shape["dp"] == len(jax.devices())


def test_global_device_mesh_2d_with_shape():
    n = len(jax.devices())
    if n % 2:
        pytest.skip("needs an even device count")
    mesh = global_device_mesh(("dp", "sp"), (n // 2, 2))
    assert dict(mesh.shape) == {"dp": n // 2, "sp": 2}
    assert np.asarray(mesh.devices).size == n


def test_global_device_mesh_multi_axis_requires_shape():
    with pytest.raises(ValueError, match="shape is required"):
        global_device_mesh(("dp", "sp"))
