import os

test_data_folder = os.path.join(os.path.dirname(__file__), "data")
temporary_files_folder = os.path.join(os.path.dirname(__file__), "_tmp")
os.makedirs(temporary_files_folder, exist_ok=True)
# golden fixtures from the reference checkout, used read-only when present
reference_data_folder = "/root/reference/data/unittest"


def has_reference_data():
    return os.path.isdir(reference_data_folder)
