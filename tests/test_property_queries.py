"""Property-based invariants for the query kernels (hypothesis).

The parity suites pin specific fixtures; these generate adversarial
geometry — degenerate faces, coincident vertices, extreme scales — and
assert invariants that must hold for ANY input:

- closest-point distance equals the f64 brute-force oracle (exactness);
- reported points lie on the reported face (consistency);
- triangle-triangle intersection is symmetric in its arguments;
- self-intersection counting never exceeds F and is 0 for a convex hull
  shape (icosphere), regardless of scale/translation.

Example counts are kept small: the point is the generator's shapes, not
volume.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from mesh_tpu.query import (
    closest_faces_and_points,
    intersections_mask,
    self_intersection_count,
)
from mesh_tpu.query.point_triangle import closest_point_on_triangle

from .fixtures import icosphere

_SETTINGS = dict(max_examples=25, deadline=None)


def _mesh_strategy(max_v=24, max_f=40):
    """Random triangle soup, possibly with degenerate / repeated faces."""
    return st.integers(0, 2 ** 31 - 1).map(_build_soup(max_v, max_f))


def _build_soup(max_v, max_f):
    def build(seed):
        rng = np.random.RandomState(seed % (2 ** 31))
        n_v = rng.randint(4, max_v)
        n_f = rng.randint(1, max_f)
        v = rng.randn(n_v, 3)
        # mix of scales, incl. tiny and large
        v *= 10.0 ** rng.randint(-2, 3)
        f = rng.randint(0, n_v, size=(n_f, 3))
        if rng.rand() < 0.5 and n_f > 1:
            f[n_f // 2] = f[0]                     # duplicate face
        if rng.rand() < 0.5:
            f[0, 1] = f[0, 0]                      # degenerate edge
        return v.astype(np.float32), f.astype(np.int32)

    return build


def _oracle_min_sqdist(v, f, pts):
    """f64 exact min squared distance over all faces, plus the f32-scale
    tolerance both oracle tests assert against."""
    tri = v[f].astype(np.float64)
    _, sq, _ = closest_point_on_triangle(
        pts.astype(np.float64)[:, None], tri[:, 0], tri[:, 1], tri[:, 2]
    )
    scale = max(1.0, float(np.abs(v).max()) ** 2)
    return np.asarray(sq).min(axis=1), scale


@settings(**_SETTINGS)
@given(_mesh_strategy(), st.integers(0, 2 ** 31 - 1))
def test_closest_point_matches_f64_oracle(mesh, qseed):
    v, f = mesh
    rng = np.random.RandomState(qseed % (2 ** 31))
    pts = (rng.randn(8, 3) * np.abs(v).max()).astype(np.float32)
    res = closest_faces_and_points(v, f, pts, chunk=8)
    oracle, scale = _oracle_min_sqdist(v, f, pts)
    got = np.asarray(res["sqdist"], np.float64)
    np.testing.assert_allclose(got, oracle, atol=2e-4 * scale, rtol=2e-4)


@settings(**_SETTINGS)
@given(_mesh_strategy(), st.integers(0, 2 ** 31 - 1),
       st.sampled_from(["fast", "safe"]),
       st.sampled_from(["exact", "fused"]))
def test_pallas_tile_variants_match_f64_oracle(mesh, qseed, variant,
                                               reduction):
    # the round-5 kernel variants under the same adversarial generator
    # (degenerate faces, coincident vertices, extreme scales), interpret
    # mode: reported distance must match the f64 exact minimum within
    # each variant's documented bound (the fused reduction adds its
    # 2^-(23-log2(TF)) relative tie radius on top of f32 rounding)
    from mesh_tpu.query.pallas_closest import closest_point_pallas

    v, f = mesh
    rng = np.random.RandomState(qseed % (2 ** 31))
    pts = (rng.randn(8, 3) * np.abs(v).max()).astype(np.float32)
    tile_f = 32
    res = closest_point_pallas(
        v, f, pts, tile_q=8, tile_f=tile_f, interpret=True,
        tile_variant=variant, reduction=reduction)
    oracle, scale = _oracle_min_sqdist(v, f, pts)
    got = np.asarray(res["sqdist"], np.float64)
    tie = (2.0 ** -(23 - int(np.log2(tile_f)))
           if reduction == "fused" else 0.0)
    np.testing.assert_allclose(
        got, oracle, atol=2e-4 * scale, rtol=2e-4 + 4 * tie)


@settings(**_SETTINGS)
@given(_mesh_strategy(), st.integers(0, 2 ** 31 - 1))
def test_reported_point_lies_on_reported_face(mesh, qseed):
    v, f = mesh
    rng = np.random.RandomState(qseed % (2 ** 31))
    pts = (rng.randn(6, 3) * np.abs(v).max()).astype(np.float32)
    res = closest_faces_and_points(v, f, pts, chunk=8)
    face = np.asarray(res["face"], np.int64)
    point = np.asarray(res["point"], np.float64)
    tri = v[f].astype(np.float64)[face]           # [Q, 3, 3]
    # the reported point must be (within rounding) the closest point ON
    # the reported face: re-projecting it onto that face is a fixpoint
    _, sq, _ = closest_point_on_triangle(
        point[:, None], tri[:, None, 0], tri[:, None, 1], tri[:, None, 2]
    )
    scale = max(1.0, float(np.abs(v).max()) ** 2)
    assert float(np.asarray(sq).max()) < 2e-4 * scale


@settings(**_SETTINGS)
@given(st.integers(0, 2 ** 31 - 1))
def test_tri_tri_mask_symmetric(seed):
    rng = np.random.RandomState(seed % (2 ** 31))
    v1 = rng.randn(12, 3).astype(np.float32)
    f1 = rng.randint(0, 12, size=(8, 3)).astype(np.int32)
    v2 = (rng.randn(12, 3) * 0.8).astype(np.float32)
    f2 = rng.randint(0, 12, size=(8, 3)).astype(np.int32)
    m12 = np.asarray(intersections_mask(v1, f1, v2, f2, chunk=8))
    m21 = np.asarray(intersections_mask(v2, f2, v1, f1, chunk=8))
    # any-intersection must agree in aggregate: if some face of mesh2
    # crosses mesh1, then some face of mesh1 crosses mesh2
    assert m12.any() == m21.any()


@settings(**_SETTINGS)
@given(
    st.floats(0.01, 100.0),
    st.floats(-5.0, 5.0),
    st.integers(1, 2),
)
def test_convex_shape_never_self_intersects(scale, shift, level):
    v, f = icosphere(level)
    v = (v * scale + shift).astype(np.float32)
    count = int(self_intersection_count(v, f.astype(np.int32), chunk=64))
    assert count == 0


@settings(**_SETTINGS)
@given(_mesh_strategy(max_v=16, max_f=24), st.integers(0, 2 ** 31 - 1))
def test_self_intersection_count_invariant_under_face_order(mesh, pseed):
    # involved-face counting must not depend on face ordering or rigid
    # motion — falsifiable for tolerance/indexing bugs, unlike a bound
    v, f = mesh
    count = int(self_intersection_count(v, f, chunk=16))
    rng = np.random.RandomState(pseed % (2 ** 31))
    perm = rng.permutation(f.shape[0])
    assert int(self_intersection_count(v, f[perm], chunk=16)) == count
    shifted = (v + np.float32(3.5)).astype(np.float32)
    assert int(self_intersection_count(shifted, f, chunk=16)) == count


@settings(**_SETTINGS)
@given(_mesh_strategy(max_v=20, max_f=30), st.integers(0, 2 ** 31 - 1))
def test_nearest_alongnormal_hit_lies_on_line_and_face(mesh, qseed):
    """Any finite nearest_alongnormal result must (a) lie on the query's
    normal line at distance `dist` and (b) lie on the reported face — the
    two halves of the reference contract (spatialsearchmodule.cpp:275-321),
    checked on random soup including degenerate faces."""
    from mesh_tpu.query.ray import NO_HIT, nearest_alongnormal

    v, f = mesh
    rng = np.random.RandomState(qseed % (2 ** 31))
    pts = (rng.randn(12, 3) * np.abs(v).max()).astype(np.float32)
    nrm = rng.randn(12, 3).astype(np.float32)
    nrm /= np.maximum(np.linalg.norm(nrm, axis=1, keepdims=True), 1e-9)
    dist, face, point = nearest_alongnormal(v, f, pts, nrm)
    dist = np.asarray(dist)
    face = np.asarray(face)
    point = np.asarray(point)
    hit = dist < NO_HIT / 2
    if not hit.any():
        return
    scale = max(float(np.abs(v).max()), 1.0)
    # on the line: |point - pts| == dist (both signs allowed)
    along = np.linalg.norm(point[hit] - pts[hit], axis=1)
    np.testing.assert_allclose(along, dist[hit], atol=2e-4 * scale,
                               rtol=2e-4)
    # on the face: exact point-triangle distance ~ 0
    tri = v[f]
    t = tri[face[hit]]
    _, sq, _ = closest_point_on_triangle(
        point[hit], t[:, 0], t[:, 1], t[:, 2]
    )
    assert np.asarray(sq).max() <= (1e-3 * scale) ** 2


@settings(**_SETTINGS)
@given(st.integers(0, 2 ** 31 - 1), st.floats(1.5, 4.0))
def test_visibility_unoccluded_sphere_all_visible(seed, cam_r):
    """A camera outside a convex mesh sees every vertex on its own side
    (n.dir clear of the polyhedral silhouette margin) —
    the analytic half of the reference's box fixture, randomized."""
    from mesh_tpu.query import visibility_compute

    v, f = icosphere(1)
    v = v.astype(np.float32)
    rng = np.random.RandomState(seed % (2 ** 31))
    cam_dir = rng.randn(3)
    cam_dir /= np.linalg.norm(cam_dir)
    cam = (cam_dir * cam_r).astype(np.float32)[None]
    vis, ndc = visibility_compute(v, f.astype(np.int32), cam)
    vis = np.asarray(vis)[0].astype(bool)
    # the polyhedron's silhouette deviates from the smooth sphere's by up
    # to the worst face-normal-vs-radial angle (chordal faces): margins
    # tighter than that flag genuinely-unoccluded vertices as "away"
    # (found by this test's first run — vertex at dot=-0.306 with the
    # nearest face missing its ray by barycentric slack 0.058)
    tri = v[f]
    fn = np.cross(tri[:, 1] - tri[:, 0], tri[:, 2] - tri[:, 0])
    fn /= np.linalg.norm(fn, axis=1, keepdims=True)
    corner_dir = tri / np.linalg.norm(tri, axis=2, keepdims=True)
    worst_cos = np.einsum("fj,fcj->fc", fn, corner_dir).min()
    margin = np.sqrt(1.0 - worst_cos ** 2) + 0.05
    # every vertex whose outward normal clearly faces the camera is visible
    outward = v / np.linalg.norm(v, axis=1, keepdims=True)
    dirs = cam[0] - v
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    facing = (outward * dirs).sum(1) > margin
    assert vis[facing].all()
    # and nothing well past the polyhedral silhouette is visible
    away = (outward * dirs).sum(1) < -margin
    assert away.any() and not vis[away].any()
