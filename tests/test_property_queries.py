"""Property-based invariants for the query kernels (hypothesis).

The parity suites pin specific fixtures; these generate adversarial
geometry — degenerate faces, coincident vertices, extreme scales — and
assert invariants that must hold for ANY input:

- closest-point distance equals the f64 brute-force oracle (exactness);
- reported points lie on the reported face (consistency);
- triangle-triangle intersection is symmetric in its arguments;
- self-intersection counting never exceeds F and is 0 for a convex hull
  shape (icosphere), regardless of scale/translation.

Example counts are kept small: the point is the generator's shapes, not
volume.
"""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

from mesh_tpu.query import (
    closest_faces_and_points,
    intersections_mask,
    self_intersection_count,
)
from mesh_tpu.query.point_triangle import closest_point_on_triangle

from .fixtures import icosphere

_SETTINGS = dict(max_examples=25, deadline=None)


def _mesh_strategy(max_v=24, max_f=40):
    """Random triangle soup, possibly with degenerate / repeated faces."""
    return st.integers(0, 2 ** 31 - 1).map(_build_soup(max_v, max_f))


def _build_soup(max_v, max_f):
    def build(seed):
        rng = np.random.RandomState(seed % (2 ** 31))
        n_v = rng.randint(4, max_v)
        n_f = rng.randint(1, max_f)
        v = rng.randn(n_v, 3)
        # mix of scales, incl. tiny and large
        v *= 10.0 ** rng.randint(-2, 3)
        f = rng.randint(0, n_v, size=(n_f, 3))
        if rng.rand() < 0.5 and n_f > 1:
            f[n_f // 2] = f[0]                     # duplicate face
        if rng.rand() < 0.5:
            f[0, 1] = f[0, 0]                      # degenerate edge
        return v.astype(np.float32), f.astype(np.int32)

    return build


@settings(**_SETTINGS)
@given(_mesh_strategy(), st.integers(0, 2 ** 31 - 1))
def test_closest_point_matches_f64_oracle(mesh, qseed):
    v, f = mesh
    rng = np.random.RandomState(qseed % (2 ** 31))
    pts = (rng.randn(8, 3) * np.abs(v).max()).astype(np.float32)
    res = closest_faces_and_points(v, f, pts, chunk=8)
    # f64 oracle: exact min over all faces
    tri = v[f].astype(np.float64)
    _, sq, _ = closest_point_on_triangle(
        pts.astype(np.float64)[:, None], tri[:, 0], tri[:, 1], tri[:, 2]
    )
    oracle = np.asarray(sq).min(axis=1)
    got = np.asarray(res["sqdist"], np.float64)
    scale = max(1.0, float(np.abs(v).max()) ** 2)
    np.testing.assert_allclose(got, oracle, atol=2e-4 * scale, rtol=2e-4)


@settings(**_SETTINGS)
@given(_mesh_strategy(), st.integers(0, 2 ** 31 - 1))
def test_reported_point_lies_on_reported_face(mesh, qseed):
    v, f = mesh
    rng = np.random.RandomState(qseed % (2 ** 31))
    pts = (rng.randn(6, 3) * np.abs(v).max()).astype(np.float32)
    res = closest_faces_and_points(v, f, pts, chunk=8)
    face = np.asarray(res["face"], np.int64)
    point = np.asarray(res["point"], np.float64)
    tri = v[f].astype(np.float64)[face]           # [Q, 3, 3]
    # the reported point must be (within rounding) the closest point ON
    # the reported face: re-projecting it onto that face is a fixpoint
    _, sq, _ = closest_point_on_triangle(
        point[:, None], tri[:, None, 0], tri[:, None, 1], tri[:, None, 2]
    )
    scale = max(1.0, float(np.abs(v).max()) ** 2)
    assert float(np.asarray(sq).max()) < 2e-4 * scale


@settings(**_SETTINGS)
@given(st.integers(0, 2 ** 31 - 1))
def test_tri_tri_mask_symmetric(seed):
    rng = np.random.RandomState(seed % (2 ** 31))
    v1 = rng.randn(12, 3).astype(np.float32)
    f1 = rng.randint(0, 12, size=(8, 3)).astype(np.int32)
    v2 = (rng.randn(12, 3) * 0.8).astype(np.float32)
    f2 = rng.randint(0, 12, size=(8, 3)).astype(np.int32)
    m12 = np.asarray(intersections_mask(v1, f1, v2, f2, chunk=8))
    m21 = np.asarray(intersections_mask(v2, f2, v1, f1, chunk=8))
    # any-intersection must agree in aggregate: if some face of mesh2
    # crosses mesh1, then some face of mesh1 crosses mesh2
    assert m12.any() == m21.any()


@settings(**_SETTINGS)
@given(
    st.floats(0.01, 100.0),
    st.floats(-5.0, 5.0),
    st.integers(1, 2),
)
def test_convex_shape_never_self_intersects(scale, shift, level):
    v, f = icosphere(level)
    v = (v * scale + shift).astype(np.float32)
    count = int(self_intersection_count(v, f.astype(np.int32), chunk=64))
    assert count == 0


@settings(**_SETTINGS)
@given(_mesh_strategy(max_v=16, max_f=24), st.integers(0, 2 ** 31 - 1))
def test_self_intersection_count_invariant_under_face_order(mesh, pseed):
    # involved-face counting must not depend on face ordering or rigid
    # motion — falsifiable for tolerance/indexing bugs, unlike a bound
    v, f = mesh
    count = int(self_intersection_count(v, f, chunk=16))
    rng = np.random.RandomState(pseed % (2 ** 31))
    perm = rng.permutation(f.shape[0])
    assert int(self_intersection_count(v, f[perm], chunk=16)) == count
    shifted = (v + np.float32(3.5)).astype(np.float32)
    assert int(self_intersection_count(shifted, f, chunk=16)) == count
