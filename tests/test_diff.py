"""Gradient correctness of mesh_tpu.diff (CPU, mostly float64).

The envelope-theorem VJPs are checked three ways:

1. against a dense *differentiable* O(Q*F) reference — barycentric
   closest point on every face, ``jnp.min`` over faces — whose jax.grad
   is trustworthy because it contains no custom rules;
2. against central finite differences of the primal (f64, 1e-5);
3. frozen vs ``mode="recompute"`` must agree exactly away from
   argmin ties (the modes differ only in how the winning simplex is
   linearized, not in which simplex wins).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mesh_tpu import diff
from mesh_tpu.query.point_triangle import closest_point_barycentric
from tests.fixtures import icosphere, separated_sphere_queries


def _dense_min_sqdist(v, f, pts):
    """Differentiable O(Q*F) reference: min over ALL faces of the exact
    point-triangle squared distance (no argmin freezing anywhere)."""
    tri = v[f]  # [F, 3, 3]
    bary, _ = closest_point_barycentric(
        pts[:, None, :], tri[None, :, 0], tri[None, :, 1], tri[None, :, 2]
    )
    cp = jnp.einsum("qfk,fkd->qfd", bary, tri)
    sq = jnp.sum((pts[:, None, :] - cp) ** 2, axis=-1)
    return jnp.min(sq, axis=-1)


def _f64_case(subdiv=1, n_q=24, seed=0):
    v, f = icosphere(subdiv)
    pts = separated_sphere_queries(n_q, seed)
    return (
        jnp.asarray(v, jnp.float64),
        jnp.asarray(f, jnp.int32),
        jnp.asarray(pts, jnp.float64),
    )


class TestClosestPointGrad:
    @pytest.mark.parametrize("mode", ["frozen", "recompute"])
    def test_matches_dense_reference(self, mode):
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case()

            def loss(v_, pts_):
                res = diff.closest_point(v_, f, pts_, mode=mode)
                return jnp.sum(res["sqdist"])

            def ref(v_, pts_):
                return jnp.sum(_dense_min_sqdist(v_, f, pts_))

            gv, gp = jax.grad(loss, argnums=(0, 1))(v, pts)
            rv, rp = jax.grad(ref, argnums=(0, 1))(v, pts)
            np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=1e-5)
            np.testing.assert_allclose(np.asarray(gp), np.asarray(rp), atol=1e-5)

    def test_finite_differences(self):
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case(n_q=8, seed=1)
            rng = np.random.RandomState(2)
            dv = jnp.asarray(rng.randn(*v.shape), jnp.float64)
            dp = jnp.asarray(rng.randn(*pts.shape), jnp.float64)

            def loss(v_, pts_):
                return jnp.sum(diff.closest_point(v_, f, pts_)["sqdist"])

            gv, gp = jax.grad(loss, argnums=(0, 1))(v, pts)
            analytic = float(jnp.vdot(gv, dv) + jnp.vdot(gp, dp))
            eps = 1e-6
            fd = (
                float(loss(v + eps * dv, pts + eps * dp))
                - float(loss(v - eps * dv, pts - eps * dp))
            ) / (2 * eps)
            assert abs(analytic - fd) <= 1e-5 * max(1.0, abs(fd))

    def test_frozen_vs_recompute_consistent(self):
        """sqdist gradients must agree exactly between modes: the envelope
        theorem zeroes the bary-derivative term at the distance minimum,
        so freezing bary loses nothing.  (The ``point`` output is NOT
        covered by the theorem — its recompute gradient keeps the
        tangential motion of the projection, frozen drops it by design —
        so the comparison is deliberately restricted to sqdist.)"""
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case(n_q=16, seed=3)
            rng = np.random.RandomState(30)
            w = jnp.asarray(rng.rand(16), jnp.float64)

            def loss(mode):
                def inner(v_, pts_):
                    res = diff.closest_point(v_, f, pts_, mode=mode)
                    return jnp.sum(w * res["sqdist"])
                return inner

            gf = jax.grad(loss("frozen"), argnums=(0, 1))(v, pts)
            gr = jax.grad(loss("recompute"), argnums=(0, 1))(v, pts)
            for a, b in zip(gf, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-8)

    def test_jvp_through_recompute(self):
        """Forward-mode must work in recompute mode (the frozen custom_vjp
        deliberately has no JVP rule; recompute re-derives barycentrics
        differentiably so jax.jvp composes)."""
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case(n_q=6, seed=4)
            rng = np.random.RandomState(5)
            dv = jnp.asarray(rng.randn(*v.shape), jnp.float64)

            def prim(v_):
                return diff.closest_point(v_, f, pts, mode="recompute")["sqdist"]

            _, tangent = jax.jvp(prim, (v,), (dv,))
            gv = jax.grad(lambda v_: jnp.sum(prim(v_)))(v)
            np.testing.assert_allclose(
                float(jnp.sum(tangent)), float(jnp.vdot(gv, dv)), rtol=1e-9
            )

    def test_batched_grad(self):
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case(n_q=10, seed=6)
            vb = jnp.stack([v, v * 1.1])
            pb = jnp.stack([pts, pts + 0.05])

            def loss(vb_):
                return jnp.sum(diff.closest_point_batched(vb_, f, pb)["sqdist"])

            g = jax.grad(loss)(vb)
            assert g.shape == vb.shape
            assert bool(jnp.all(jnp.isfinite(g)))


class TestPointToTriangleGrad:
    def test_matches_dense_reference(self):
        with jax.experimental.enable_x64():
            rng = np.random.RandomState(7)
            p = jnp.asarray(rng.randn(12, 3), jnp.float64)
            a = jnp.asarray(rng.randn(12, 3), jnp.float64)
            b = jnp.asarray(rng.randn(12, 3), jnp.float64)
            c = jnp.asarray(rng.randn(12, 3), jnp.float64)

            def loss(p_, a_, b_, c_):
                return jnp.sum(diff.point_to_triangle(p_, a_, b_, c_)["sqdist"])

            def ref(p_, a_, b_, c_):
                bary, _ = closest_point_barycentric(p_, a_, b_, c_)
                cp = jnp.einsum(
                    "qk,qkd->qd", bary, jnp.stack([a_, b_, c_], axis=-2)
                )
                return jnp.sum(jnp.sum((p_ - cp) ** 2, axis=-1))

            g = jax.grad(loss, argnums=(0, 1, 2, 3))(p, a, b, c)
            r = jax.grad(ref, argnums=(0, 1, 2, 3))(p, a, b, c)
            for gi, ri in zip(g, r):
                np.testing.assert_allclose(np.asarray(gi), np.asarray(ri), atol=1e-5)


class TestEnergies:
    def test_point_to_plane_grad_finite(self):
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case(n_q=12, seed=8)
            g = jax.grad(lambda v_: diff.point_to_plane(v_, f, pts))(v)
            assert bool(jnp.all(jnp.isfinite(g)))

    @pytest.mark.parametrize("robust", [None, ("huber", 0.1), ("geman_mcclure", 0.1)])
    def test_point_to_point_robust_grad_finite(self, robust):
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case(n_q=12, seed=9)
            g = jax.grad(
                lambda v_: diff.point_to_point(v_, f, pts, robust=robust)
            )(v)
            assert bool(jnp.all(jnp.isfinite(g)))

    def test_symmetric_chamfer_grad_finite(self):
        with jax.experimental.enable_x64():
            v, f, pts = _f64_case(n_q=12, seed=10)
            g = jax.grad(lambda v_: diff.symmetric_chamfer(v_, f, pts))(v)
            assert bool(jnp.all(jnp.isfinite(g)))

    def test_robust_kernels_reduce_to_identity_near_zero(self):
        sq = jnp.asarray([1e-8, 1e-6], jnp.float32)
        np.testing.assert_allclose(
            np.asarray(diff.huber(sq, delta=1.0)), np.asarray(sq), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(diff.geman_mcclure(sq, sigma=1.0)),
            np.asarray(sq),
            rtol=1e-4,
        )


class TestFitLossSurface:
    def test_default_data_term_is_surface(self):
        from mesh_tpu.parallel.fit import _resolve_data_term

        assert _resolve_data_term(None) == "surface"
        assert _resolve_data_term("vertex") == "vertex"

    def test_fit_loss_grad_nan_free_on_sliver_mesh(self):
        """The sliver-heavy synthetic template must not poison the fit
        gradients: the surface data term's backward touches only the
        winning simplex via frozen barycentrics, so degenerate faces a
        query does NOT project onto contribute nothing."""
        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.parallel.fit import scan_to_model_loss

        v, f = icosphere(1)
        v = np.asarray(v, np.float32)
        f = np.asarray(f, np.int32)
        n0 = len(v)
        # graft a point-triangle and a collinear sliver onto the template
        v = np.vstack([
            v,
            [[0.0, 0.0, 1.5], [-0.5, 0.0, 1.5], [0.5, 0.0, 1.5]],
        ]).astype(np.float32)
        f = np.vstack(
            [f, [[n0, n0, n0], [n0, n0 + 1, n0 + 2]]]
        ).astype(np.int32)
        model = synthetic_body_model(seed=0, template=(v, f))
        rng = np.random.RandomState(11)
        scan = jnp.asarray(rng.randn(1, 40, 3) * 0.3, jnp.float32)
        betas = jnp.zeros((1, model.num_betas))
        pose = jnp.zeros((1, model.num_joints, 3))
        trans = jnp.zeros((1, 3))

        def loss(betas_, pose_, trans_):
            return scan_to_model_loss(model, betas_, pose_, trans_, scan)

        grads = jax.grad(loss, argnums=(0, 1, 2))(betas, pose, trans)
        for g in grads:
            assert bool(jnp.all(jnp.isfinite(g)))

    def test_vertex_term_still_available(self):
        from mesh_tpu.models import synthetic_body_model
        from mesh_tpu.parallel.fit import scan_to_model_loss

        model = synthetic_body_model(seed=0)
        rng = np.random.RandomState(12)
        scan = jnp.asarray(rng.randn(1, 30, 3) * 0.3, jnp.float32)
        z = jnp.zeros((1, model.num_betas))
        pose = jnp.zeros((1, model.num_joints, 3))
        t = jnp.zeros((1, 3))
        a = float(scan_to_model_loss(model, z, pose, t, scan, data_term="surface"))
        b = float(scan_to_model_loss(model, z, pose, t, scan, data_term="vertex"))
        assert np.isfinite(a) and np.isfinite(b)
        # surface distance is a lower bound on vertex distance
        assert a <= b + 1e-6


class TestRegister:
    def test_icp_descends_and_hits_plan_cache(self):
        """Acceptance: ICP re-correspondence goes through the engine and
        the repeated same-shape bursts hit the plan cache (hits > misses
        after warmup)."""
        from mesh_tpu.engine import stats

        v, f = icosphere(2)
        rng = np.random.RandomState(13)
        scan = (np.asarray(v) * 1.15 + rng.randn(*v.shape) * 0.01).astype(
            np.float32
        )[: 120]
        before = stats()["plan_cache"]
        res = diff.register_vertices(
            v.astype(np.float32), f, scan, steps=6, recorrespond_every=2
        )
        after = stats()["plan_cache"]
        assert res.losses[-1] < res.losses[0]
        assert res.recorrespondences == 3
        d_hits = after["hits"] - before["hits"]
        d_misses = after["misses"] - before["misses"]
        assert d_hits > d_misses

    def test_register_records_obs(self, monkeypatch):
        monkeypatch.setenv("MESH_TPU_OBS", "1")
        from mesh_tpu.obs import metrics_snapshot

        v, f = icosphere(1)
        rng = np.random.RandomState(14)
        scan = (np.asarray(v) * 1.1 + rng.randn(*v.shape) * 0.01).astype(
            np.float32
        )
        diff.register_vertices(
            v.astype(np.float32), f, scan, steps=4, recorrespond_every=2
        )
        snap = metrics_snapshot()
        assert "mesh_tpu_diff_recorrespond_total" in snap
        assert "mesh_tpu_diff_residual_meters" in snap
