"""Plane-section circumference (mesh_tpu/metrics.py) — the measurement the
reference removed from its core package (reference mesh.py:313-314) —
plus return-shape parity assertions for the search trees (reference
search.py:52-100 conventions)."""

import numpy as np
import pytest

from mesh_tpu import Mesh
from mesh_tpu.metrics import circumference, plane_section

from .fixtures import box, cylinder, icosphere


class TestPlaneSection:
    def test_box_midslice_is_square_perimeter(self):
        v, f = box(size=2.0)
        m = Mesh(v=v, f=f)
        c = m.estimate_circumference([0.0, 0.0, 1.0], 0.0)
        assert c == pytest.approx(8.0, rel=1e-12)

    def test_cylinder_slice_matches_polygon_perimeter(self):
        n = 64
        v, f = cylinder(n=n, radius=1.0, height=2.0)
        # the n-gon ring has perimeter 2*n*sin(pi/n), not 2*pi
        expected = 2 * n * np.sin(np.pi / n)
        c = circumference(Mesh(v=v, f=f), [0, 0, 1], 0.3)
        assert c == pytest.approx(expected, rel=1e-9)

    def test_sphere_slice_approaches_great_circle(self):
        v, f = icosphere(subdivisions=3)
        c = circumference(Mesh(v=v, f=f), [1.0, 0.0, 0.0], 0.0)
        assert c == pytest.approx(2 * np.pi, rel=0.01)

    def test_offset_slice_is_smaller_circle(self):
        v, f = icosphere(subdivisions=3)
        d = 0.5
        c = circumference(Mesh(v=v, f=f), [0.0, 0.0, 1.0], d)
        assert c == pytest.approx(2 * np.pi * np.sqrt(1 - d * d), rel=0.01)

    def test_edges_lie_on_plane(self):
        v, f = icosphere(subdivisions=2)
        n = np.array([1.0, 2.0, 3.0])
        n = n / np.linalg.norm(n)
        total, edges = circumference(Mesh(v=v, f=f), n, 0.25, want_edges=True)
        assert edges.shape[1:] == (2, 3)
        assert total > 0
        np.testing.assert_allclose(edges.reshape(-1, 3) @ n, 0.25, atol=1e-9)

    def test_unnormalized_normal_keeps_plane_equation(self):
        # dot([0,0,2], x) = 1  is the plane z = 0.5, whatever ||n|| is
        v, f = box(size=2.0)
        m = Mesh(v=v, f=f)
        c_unit = m.estimate_circumference([0.0, 0.0, 1.0], 0.5)
        c_scaled = m.estimate_circumference([0.0, 0.0, 2.0], 1.0)
        assert c_scaled == pytest.approx(c_unit, rel=1e-12)

    def test_missing_plane_returns_zero(self):
        v, f = box(size=1.0)
        assert circumference(Mesh(v=v, f=f), [0, 0, 1], 5.0) == 0.0

    def test_part_restriction(self):
        v, f = box(size=2.0)
        m = Mesh(v=v, f=f)
        # side walls only: drop the z-normal caps (which the z=0 plane
        # misses anyway) -> same perimeter; empty selection -> zero
        m.segm = {"walls": np.arange(4, 12), "caps": np.arange(0, 4)}
        assert m.estimate_circumference(
            [0, 0, 1], 0.0, partNamesAllowed=["walls"]
        ) == pytest.approx(8.0)
        assert m.estimate_circumference(
            [0, 0, 1], 0.0, partNamesAllowed=["caps"]
        ) == 0.0
        assert m.estimate_circumference(
            [0, 0, 1], 0.0, partNamesAllowed=["nope"]
        ) == 0.0

    def test_on_plane_vertices_do_not_crash(self):
        # a vertex exactly on the plane exercises the eps tie-break
        v, f = box(size=2.0)
        c = plane_section(v, f, [0, 0, 1], 1.0)
        assert c[0].shape[1] == 3


class TestSearchReturnShapeParity:
    """The reference's tree classes have exact return conventions
    (search.py:26-30, 59-65, 78-86); drop-in callers index into them."""

    def setup_method(self):
        v, f = icosphere(subdivisions=1)
        self.m = Mesh(v=v, f=f)
        self.q = np.random.RandomState(7).randn(5, 3)

    def test_aabb_tree_nearest_shapes(self):
        # reference: f_idxs (1, S), f_part (1, S), points (S, 3)
        tree = self.m.compute_aabb_tree()
        f_idxs, points = tree.nearest(self.q)
        assert np.asarray(f_idxs).shape == (1, 5)
        assert np.asarray(points).shape == (5, 3)
        f_idxs, f_part, points = tree.nearest(self.q, nearest_part=True)
        assert np.asarray(f_part).shape == (1, 5)

    def test_closest_point_tree_shapes(self):
        tree = self.m.compute_closest_point_tree()
        idx, dist = tree.nearest(self.q)
        assert np.asarray(idx).shape == (5,)
        assert np.asarray(dist).shape == (5,)
        assert tree.nearest_vertices(self.q).shape == (5, 3)

    def test_cgal_closest_point_tree_shapes(self):
        tree = self.m.compute_closest_point_tree(use_cgal=True)
        idx, dist = tree.nearest(self.q)
        assert np.asarray(idx).shape == (5,)
        assert np.asarray(dist).shape == (5,)
        assert tree.nearest_vertices(self.q).shape == (5, 3)

    def test_closest_faces_and_points_shapes(self):
        faces, points = self.m.closest_faces_and_points(self.q)
        # reference mesh.py:454-455 returns column face ids + (S, 3) points
        assert np.asarray(points).shape == (5, 3)
        assert np.asarray(faces).size == 5
