"""Round-5 closest-point tile variants (interpret mode).

Covers the two opt-in kernel variants added for VERDICT r4 #4/#7:

- ``tile_variant="safe"`` — the sliver-safe direct-corner tile
  (pallas_closest._sqdist_tile_safe): every Ericson term computed from its
  own corner difference, no ap2-scale cancellation.
- ``reduction="fused"`` — the packed single-pass min+argmin
  (make_fused_argmin_kernel): one int32 min reduction instead of a min
  pass plus an argmin pass, at a documented 2^-(23-log2(TF)) relative tie
  radius.

Both must agree with the exact XLA reference on distances everywhere; the
fused reduction may flip faces only inside its tie radius.  The compiled
counterparts live in tests/test_tpu_compiled.py.
"""

import numpy as np
import pytest

from .fixtures import separated_sphere_queries as _separated_queries

from mesh_tpu.query.closest_point import closest_faces_and_points
from mesh_tpu.query.pallas_closest import closest_point_pallas


def _clean_mesh(seed=0, check=True):
    """A non-degenerate random-ish mesh: icosphere + vertex jitter."""
    from mesh_tpu.query.pallas_closest import mesh_is_nondegenerate
    from mesh_tpu.sphere import _icosphere

    v, f = _icosphere(3)
    rng = np.random.RandomState(seed)
    v = (v + 0.02 * rng.randn(*v.shape)).astype(np.float32)
    f = f.astype(np.int32)
    if check:
        assert mesh_is_nondegenerate(v, f)
    return v, f



@pytest.mark.parametrize("nondegen", [False, True])
def test_safe_tile_matches_xla(nondegen):
    v, f = _clean_mesh()
    pts = _separated_queries(257, seed=1)
    ref = closest_faces_and_points(v, f, pts)
    out = closest_point_pallas(
        v, f, pts, tile_q=64, tile_f=256, interpret=True,
        tile_variant="safe", assume_nondegenerate=nondegen)
    np.testing.assert_allclose(
        np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-5)
    # faces may differ only where two faces are near-exactly tied (the
    # two paths' arithmetic differs at rounding level; near a shared
    # edge the distance gap grows only quadratically with the offset, so
    # a sqrt(eps)-wide band of queries ties legitimately)
    flipped = np.asarray(out["face"]) != np.asarray(ref["face"])
    assert flipped.mean() < 0.15, flipped.mean()
    sq_o = np.asarray(out["sqdist"], np.float64)[flipped]
    sq_r = np.asarray(ref["sqdist"], np.float64)[flipped]
    np.testing.assert_allclose(sq_o, sq_r, rtol=1e-5, atol=1e-7)


def test_safe_tile_degenerate_faces_exact():
    # the safe tile keeps the degenerate-face override by default: a mesh
    # with zero-area faces must still be exact (segment minimum)
    v = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [2, 0, 0],
                  [0.5, 0.5, 2.0]], np.float32)
    f = np.array([[0, 1, 2], [0, 1, 3], [0, 4, 4]], np.int32)  # 2 degenerate
    rng = np.random.RandomState(2)
    pts = rng.randn(64, 3).astype(np.float32)
    ref = closest_faces_and_points(v, f, pts)
    out = closest_point_pallas(
        v, f, pts, tile_q=8, tile_f=8, interpret=True, tile_variant="safe")
    np.testing.assert_allclose(
        np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), atol=1e-6)


@pytest.mark.parametrize("tile_variant", ["fast", "safe"])
def test_fused_reduction_tie_radius(tile_variant):
    # the fused winner's exact (epilogue-recomputed) distance may exceed
    # the true minimum only by the documented packed-mask tie radius:
    # 2^-(23 - log2(TF)) relative
    v, f = _clean_mesh(seed=3)
    pts = _separated_queries(300, seed=4)
    tile_f = 256
    exact = closest_point_pallas(
        v, f, pts, tile_q=64, tile_f=tile_f, interpret=True,
        tile_variant=tile_variant)
    fused = closest_point_pallas(
        v, f, pts, tile_q=64, tile_f=tile_f, interpret=True,
        tile_variant=tile_variant, reduction="fused")
    sq_e = np.asarray(exact["sqdist"], np.float64)
    sq_f = np.asarray(fused["sqdist"], np.float64)
    radius = 2.0 ** -(23 - int(np.log2(tile_f)))
    assert np.all(sq_f <= sq_e * (1 + 4 * radius) + 1e-12), (
        "fused winner exceeded the documented tie radius: %g"
        % np.max(sq_f - sq_e))
    # flips concentrate in the sqrt(radius)-wide near-edge tie bands;
    # the tie-radius clause above is the contract, the rate check only
    # guards against gross misrouting (e.g. a broken index unpack)
    agree = (np.asarray(fused["face"]) == np.asarray(exact["face"])).mean()
    assert agree > 0.6, agree


def test_fused_reduction_padded_faces_never_win():
    # odd face count -> padded tile columns; _BIG packs to a huge key
    v, f = _clean_mesh(seed=5)
    f = f[:101]                       # not a multiple of any tile size
    rng = np.random.RandomState(6)
    pts = rng.randn(65, 3).astype(np.float32)
    out = closest_point_pallas(
        v, f, pts, tile_q=16, tile_f=32, interpret=True, reduction="fused")
    assert np.asarray(out["face"]).max() < 101
    ref = closest_faces_and_points(v, f, pts)
    np.testing.assert_allclose(
        np.asarray(out["sqdist"]), np.asarray(ref["sqdist"]), rtol=1e-4,
        atol=1e-6)


def test_invalid_options_raise():
    v, f = _clean_mesh(seed=7)
    pts = np.zeros((8, 3), np.float32)
    with pytest.raises(ValueError, match="tile_variant"):
        closest_point_pallas(v, f, pts, interpret=True, tile_variant="bogus")
    with pytest.raises(ValueError, match="reduction"):
        closest_point_pallas(v, f, pts, interpret=True, reduction="bogus")


def test_safe_tiles_reaches_batched_and_sharded_facades(monkeypatch):
    # the escape hatch must not stop at the single-mesh auto facade
    # (code-review round-5): the batched strategy keeps the measured
    # brute-vs-culled crossover under the flag (the culled kernel runs
    # the safe tile since PR 3 — tile_variant="safe"), and the sharded/
    # multi-host plumbing threads the variant into its shard bodies
    import inspect

    from mesh_tpu import batch
    from mesh_tpu.parallel import sharding
    from mesh_tpu.utils import dispatch

    monkeypatch.setenv("MESH_TPU_SAFE_TILES", "1")
    assert dispatch.tile_variant() == "safe"
    if dispatch.pallas_default():
        # a million-face batch must still take the culled kernel: the
        # safe variant tiles, it no longer routes around the cull
        f_big = np.zeros((10 ** 6, 3), np.int32)
        assert batch._strategy(f_big) == (True, True)
    for fn in (sharding._closest_local, sharding._closest_shard_fn,
               sharding._closest_fsharded_fn,
               sharding._closest_fsharded_ring_fn,
               batch._per_mesh_closest, batch._batch_step):
        target = getattr(fn, "__wrapped__", fn)
        assert "variant" in inspect.signature(target).parameters, fn

    monkeypatch.delenv("MESH_TPU_SAFE_TILES")
    assert dispatch.tile_variant() == "fast"


def test_safe_tiles_env_selects_safe_variant(monkeypatch):
    # MESH_TPU_SAFE_TILES pins the facade to the sliver-safe tile; observe
    # via the kernel cache key the facade's call populates
    import mesh_tpu.query.pallas_closest as pc
    from mesh_tpu.query.culled import closest_faces_and_points_auto
    from mesh_tpu.utils import dispatch

    if not dispatch.pallas_default():
        # CPU suite: the facade takes the XLA branch; assert the policy
        # helper itself instead (the TPU facade branch is covered by the
        # compiled suite)
        monkeypatch.setenv("MESH_TPU_SAFE_TILES", "1")
        assert dispatch.safe_tiles() is True
        v, f = _clean_mesh(seed=8, check=False)
        pts = np.zeros((8, 3), np.float32)
        out = closest_faces_and_points_auto(v, f, pts)
        assert out["face"].shape == (8,)
        return
    monkeypatch.setenv("MESH_TPU_SAFE_TILES", "1")
    pc._CLOSEST_KERNELS.clear()
    v, f = _clean_mesh(seed=8, check=False)
    pts = np.zeros((8, 3), np.float32)
    closest_faces_and_points_auto(v, f, pts)
    assert any(key[0] == "safe" for key in pc._CLOSEST_KERNELS)
